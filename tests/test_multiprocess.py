"""True multi-process distributed training test.

The reference only ever exercises "distributed" behavior on a multi-core
local[*] Spark (SURVEY §4); this goes further: two OS processes join the JAX
distributed runtime, each ingests only its host-local half of the dataset,
and the sharded solve's gradient reductions cross processes as real
collectives (Gloo on CPU — the DCN analog). Both processes must converge to
the same coefficients as a single-process solve of the full dataset.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_solve_matches_single_process(tmp_path):
    # bounded by communicate(timeout=240) below (pytest-timeout not installed)
    port = _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    worker = os.path.join(REPO, "tests", "mp_worker.py")
    # Output goes to files, not pipes: an undrained pipe can block a worker
    # mid-collective and stall its peer; files also survive for diagnosis.
    logs = [open(tmp_path / f"worker{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(tmp_path)],
            env=env,
            stdout=logs[i],
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=240)
            assert rc == 0, (
                f"worker {i} failed:\n" + (tmp_path / f"worker{i}.log").read_text()
            )
    finally:
        for p in procs:  # a failed peer must not orphan the survivor
            if p.poll() is None:
                p.kill()
        for lg in logs:
            lg.close()

    a = json.load(open(tmp_path / "proc0.json"))
    b = json.load(open(tmp_path / "proc1.json"))
    assert a["num_processes"] == b["num_processes"] == 2
    assert a["global_devices"] == 2 and a["local_devices"] == 1
    # identical single-controller results on every process
    np.testing.assert_allclose(a["coef"], b["coef"], rtol=0, atol=0)
    assert a["value"] == b["value"]

    # single-process reference on the same deterministic dataset
    import jax.numpy as jnp

    from photon_ml_tpu.data.dataset import LabeledData
    from photon_ml_tpu.parallel import make_mesh, train_glm_sharded
    from photon_ml_tpu.types import TaskType

    from mp_worker import make_config, make_dataset

    X, y = make_dataset()
    w_ref, _ = train_glm_sharded(
        LabeledData.build(X, y, dtype=jnp.float32),
        TaskType.LOGISTIC_REGRESSION,
        make_config(),
        make_mesh(1),
    )
    np.testing.assert_allclose(a["coef"], np.asarray(w_ref), atol=5e-4)
