"""True multi-process distributed training test.

The reference only ever exercises "distributed" behavior on a multi-core
local[*] Spark (SURVEY §4); this goes further: two OS processes join the JAX
distributed runtime, each ingests only its host-local half of the dataset,
and the sharded solve's gradient reductions cross processes as real
collectives (Gloo on CPU — the DCN analog). Both processes must converge to
the same coefficients as a single-process solve of the full dataset.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

import jax
import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# jax 0.4.x's CPU backend cannot back a multi-process distributed runtime
# (no Gloo cross-process collectives): every spawned worker pair dies in
# distributed.initialize regardless of the code under test. Skip — not fail —
# so tier-1 reflects code health rather than container limits; any jax >= 0.5
# or a non-CPU backend runs the suite for real. The guard lives in
# _free_port(), the single chokepoint every worker-spawning test goes
# through, so in-process tests in this file (checkpoint/resume, output modes,
# stats parity) still run everywhere.
_JAX_VERSION = tuple(int(p) for p in jax.__version__.split(".")[:2])
_COLLECTIVES_UNAVAILABLE = _JAX_VERSION < (0, 5) and jax.default_backend() == "cpu"


def _free_port():
    if _COLLECTIVES_UNAVAILABLE:
        pytest.skip(
            f"multiprocess collectives unavailable on jax {jax.__version__} "
            "CPU backend (needs jax>=0.5 or an accelerator backend)"
        )
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_solve_matches_single_process(tmp_path):
    # bounded by communicate(timeout=240) below (pytest-timeout not installed)
    port = _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    worker = os.path.join(REPO, "tests", "mp_worker.py")
    # Output goes to files, not pipes: an undrained pipe can block a worker
    # mid-collective and stall its peer; files also survive for diagnosis.
    logs = [open(tmp_path / f"worker{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(tmp_path)],
            env=env,
            stdout=logs[i],
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=240)
            assert rc == 0, (
                f"worker {i} failed:\n" + (tmp_path / f"worker{i}.log").read_text()
            )
    finally:
        for p in procs:  # a failed peer must not orphan the survivor
            if p.poll() is None:
                p.kill()
        for lg in logs:
            lg.close()

    a = json.load(open(tmp_path / "proc0.json"))
    b = json.load(open(tmp_path / "proc1.json"))
    assert a["num_processes"] == b["num_processes"] == 2
    assert a["global_devices"] == 2 and a["local_devices"] == 1
    # identical single-controller results on every process
    np.testing.assert_allclose(a["coef"], b["coef"], rtol=0, atol=0)
    assert a["value"] == b["value"]

    # single-process reference on the same deterministic dataset
    import jax.numpy as jnp

    from photon_ml_tpu.data.dataset import LabeledData
    from photon_ml_tpu.parallel import make_mesh, train_glm_sharded
    from photon_ml_tpu.types import TaskType

    from mp_worker import make_config, make_dataset

    X, y = make_dataset()
    w_ref, _ = train_glm_sharded(
        LabeledData.build(X, y, dtype=jnp.float32),
        TaskType.LOGISTIC_REGRESSION,
        make_config(),
        make_mesh(1),
    )
    np.testing.assert_allclose(a["coef"], np.asarray(w_ref), atol=5e-4)


def test_two_process_scoring_matches_single_process(tmp_path):
    """game_scoring_driver --distributed-coordinator: two processes score
    disjoint slices of the input part files and write their own output parts;
    the union must equal the single-process run exactly (the executor-parallel
    scoring of GameScoringDriver.scala)."""
    import jax.numpy as jnp
    import numpy as np

    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap
    from photon_ml_tpu.io.model_io import save_game_model
    from photon_ml_tpu.models.game import (
        FixedEffectModel,
        GameModel,
        RandomEffectModel,
    )
    from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(9)
    d, n_users, n = 4, 5, 120
    keys = [f"f{j}\x01" for j in range(d)]
    imap = IndexMap.build(keys, add_intercept=True)
    (tmp_path / "index-maps").mkdir()
    imap.save(str(tmp_path / "index-maps" / "global.npz"))

    # a hand-built GAME model: fixed effect + per-user biases
    fe_w = rng.normal(size=imap.size)
    glm = GeneralizedLinearModel(
        Coefficients(jnp.asarray(fe_w)), TaskType.LOGISTIC_REGRESSION
    )
    users = [f"u{i}" for i in range(n_users)]
    icpt = imap.intercept_index
    re_model = RandomEffectModel(
        re_type="userId",
        feature_shard_id="global",
        task=TaskType.LOGISTIC_REGRESSION,
        entity_ids=tuple(users),
        coeffs=jnp.asarray(rng.normal(size=(n_users, 1))),
        proj_indices=jnp.full((n_users, 1), icpt, dtype=jnp.int32),
    )
    gm = GameModel(models={
        "global": FixedEffectModel(model=glm, feature_shard_id="global"),
        "per-user": re_model,
    })
    save_game_model(str(tmp_path / "model"), gm, {"global": imap, "per-user": imap})

    # two input part files with top-level-free metadataMap ids
    (tmp_path / "in").mkdir()

    def records(lo, hi):
        for i in range(lo, hi):
            yield {
                # some records carry no uid: the file-anchored synthetic
                # fallback must agree between single- and multi-process runs
                "uid": None if i % 10 == 0 else f"s{i}",
                "label": float(i % 2),
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(rng.normal())}
                    for j in range(d)
                ],
                "metadataMap": {"userId": users[i % n_users]},
                "weight": 1.0,
                "offset": 0.0,
            }

    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(0, n // 2),
    )
    avro_io.write_container(
        str(tmp_path / "in" / "part-b.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(n // 2, n),
    )

    def read_scores(scores_dir):
        out = {}
        for rec in avro_io.read_container_dir(str(scores_dir)):
            out[rec["uid"]] = rec["predictionScore"]
        return out

    # single-process reference run
    from photon_ml_tpu.cli.game_scoring_driver import build_arg_parser, run

    single_args = build_arg_parser().parse_args([
        "--input-data-directories", str(tmp_path / "in"),
        "--model-input-directory", str(tmp_path / "model"),
        "--root-output-directory", str(tmp_path / "out-single"),
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
    ])
    run(single_args)
    expected = read_scores(tmp_path / "out-single" / "scores")
    assert len(expected) == n

    port = _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    worker = os.path.join(REPO, "tests", "mp_score_worker.py")
    logs = [open(tmp_path / f"scorer{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(tmp_path)],
            env=env, stdout=logs[i], stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=240)
            assert rc == 0, (
                f"scorer {i} failed:\n" + (tmp_path / f"scorer{i}.log").read_text()
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()

    parts = sorted(os.listdir(tmp_path / "out" / "scores"))
    assert parts == ["part-00000.avro", "part-00001.avro"]
    got = read_scores(tmp_path / "out" / "scores")
    assert set(got) == set(expected)
    for uid, score in expected.items():
        assert got[uid] == pytest.approx(score, rel=1e-6)


def test_two_process_training_matches_single_process(tmp_path):
    """game_training_driver --distributed-coordinator (fixed effect): two
    processes each ingest half the part files, the solve's gradient psums
    cross processes as real collectives, and the saved best model must match
    the single-process driver run — same selected reg weight, same
    coefficients."""
    import jax.numpy as jnp
    import numpy as np

    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap, feature_key

    rng = np.random.default_rng(3)
    d, n = 4, 400
    w_true = rng.normal(size=d)
    imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    (tmp_path / "index-maps").mkdir()
    imap.save(str(tmp_path / "index-maps" / "global.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            y = float((x @ w_true + 0.3 * r.normal()) > 0)
            yield {
                "uid": f"{seed}-{i}",
                "label": y,
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ],
                "metadataMap": {},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    (tmp_path / "val").mkdir()
    # UNEVEN part files: exercises the per-process padding path
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(n // 2 + 37, seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "in" / "part-b.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(n // 2 - 37, seed=2),
    )
    avro_io.write_container(
        str(tmp_path / "val" / "part-0.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(150, seed=5),
    )

    def best_coefficients(root):
        from photon_ml_tpu.io.model_io import load_game_model

        gm = load_game_model(str(root / "best"), {"global": imap})
        return gm.get_model("global").model.coefficients

    def best_coeffs(root):
        return np.asarray(best_coefficients(root).means)

    # single-process reference through the standard driver flow — WITH
    # variances, so the psum'd multi-process Hessian pass is exercised and
    # compared in a REAL 2-process run
    from photon_ml_tpu.cli.game_training_driver import build_arg_parser, run

    single = build_arg_parser().parse_args([
        "--input-data-directories", str(tmp_path / "in"),
        "--validation-data-directories", str(tmp_path / "val"),
        "--root-output-directory", str(tmp_path / "out-single"),
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
        "--training-task", "LOGISTIC_REGRESSION",
        "--coordinate-update-sequence", "global",
        "--coordinate-configurations",
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=100,"
        "tolerance=1e-9,regularization=L2,reg.weights=0.1|10",
        "--evaluators", "AUC",
        "--variance-computation-type", "SIMPLE",
    ])
    run(single)
    expected = best_coeffs(tmp_path / "out-single")

    port = _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    worker = os.path.join(REPO, "tests", "mp_train_worker.py")
    logs = [open(tmp_path / f"trainer{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(tmp_path),
             "--variance-computation-type", "SIMPLE"],
            env=env, stdout=logs[i], stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=240)
            assert rc == 0, (
                f"trainer {i} failed:\n" + (tmp_path / f"trainer{i}.log").read_text()
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()

    got = best_coeffs(tmp_path / "out")
    np.testing.assert_allclose(got, expected, atol=1e-4)
    v_ref = np.asarray(best_coefficients(tmp_path / "out-single").variances)
    v_got = np.asarray(best_coefficients(tmp_path / "out").variances)
    assert (v_got > 0).all()
    np.testing.assert_allclose(v_got, v_ref, rtol=5e-3)
    import json

    summary = json.loads((tmp_path / "out" / "summary.json").read_text())
    assert summary["num_processes"] == 2
    assert len(summary["results"]) == 2  # two reg weights trained


def test_two_process_training_wide_sparse_shard(tmp_path):
    """Multi-process training on a WIDE sparse shard (100k features, ~6
    nnz/row): the global assembly keeps COO triples (rebased to global sample
    ids, nnz-padded per process) instead of materializing dense blocks — the
    billion-feature regime of parallel/glm.py, across processes."""
    import numpy as np

    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap

    d = 100_000
    rng = np.random.default_rng(17)
    imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    (tmp_path / "index-maps").mkdir()
    imap.save(str(tmp_path / "index-maps" / "global.npz"))
    w_hot = rng.normal(size=32)  # signal lives on 32 hot features
    hot = rng.choice(d, size=32, replace=False)

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            k = 6
            js = np.concatenate([r.choice(hot, size=2), r.integers(0, d, size=k - 2)])
            xs = r.normal(size=k)
            z = sum(
                w_hot[np.where(hot == j)[0][0]] * x
                for j, x in zip(js, xs) if j in hot
            )
            yield {
                "uid": f"{seed}-{i}",
                "label": float(z + 0.3 * r.normal() > 0),
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x)}
                    for j, x in zip(js, xs)
                ],
                "metadataMap": {},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    (tmp_path / "val").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(150, seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "in" / "part-b.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(90, seed=2),
    )
    avro_io.write_container(
        str(tmp_path / "val" / "part-0.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(80, seed=5),
    )

    def best_coeffs(root):
        from photon_ml_tpu.io.model_io import load_game_model

        gm = load_game_model(str(root / "best"), {"global": imap})
        return np.asarray(gm.get_model("global").model.coefficients.means)

    from photon_ml_tpu.cli.game_training_driver import build_arg_parser, run

    single = build_arg_parser().parse_args([
        "--input-data-directories", str(tmp_path / "in"),
        "--validation-data-directories", str(tmp_path / "val"),
        "--root-output-directory", str(tmp_path / "out-single"),
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
        "--training-task", "LOGISTIC_REGRESSION",
        "--coordinate-update-sequence", "global",
        "--coordinate-configurations",
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=100,"
        "tolerance=1e-9,regularization=L2,reg.weights=0.1|10",
        "--evaluators", "AUC",
    ])
    run(single)
    expected = best_coeffs(tmp_path / "out-single")

    port = _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    worker = os.path.join(REPO, "tests", "mp_train_worker.py")
    logs = [open(tmp_path / f"trainer{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(tmp_path),
             "--variance-computation-type", "SIMPLE"],
            env=env, stdout=logs[i], stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=240)
            assert rc == 0, (
                f"trainer {i} failed:\n" + (tmp_path / f"trainer{i}.log").read_text()
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()

    got = best_coeffs(tmp_path / "out")
    assert got.shape == expected.shape == (d + 1,)
    # Equivalence, not bit-parity: 240 samples over 100k features leaves the
    # L2 optimum nearly flat along many directions, so coefficient values are
    # sensitive to f32 accumulation order (globally column-sorted segment-sum
    # single-process vs per-shard scatter-adds + psum here). Assert a modest
    # coefficient band plus the TRAINING OBJECTIVE VALUE, which is strictly
    # convex — both solves must reach the same optimum value even where the
    # argmin wiggles along flat directions.
    np.testing.assert_allclose(got, expected, atol=5e-3)

    from photon_ml_tpu.data.readers import read_merged_avro
    from photon_ml_tpu.estimators.config import FeatureShardConfiguration

    spec_single = json.load(open(tmp_path / "out-single" / "best" / "model-spec.json"))
    spec_multi = json.load(open(tmp_path / "out" / "best" / "model-spec.json"))
    assert spec_single == spec_multi  # same selected configuration
    reg = float(spec_single["global"].rsplit("reg.weights=", 1)[1])

    train_data, _, _ = read_merged_avro(
        str(tmp_path / "in"),
        {"global": FeatureShardConfiguration(feature_bags=("features",))},
        index_maps={"global": imap},
    )
    Xt = train_data.shard("global")
    y_pm = 2.0 * np.asarray(train_data.labels) - 1.0

    def objective(w):
        return float(
            np.logaddexp(0.0, -(Xt @ w) * y_pm).sum() + 0.5 * reg * w @ w
        )

    np.testing.assert_allclose(objective(got), objective(expected), rtol=1e-5)


def test_two_process_game_training_matches_single_process(tmp_path):
    """Distributed GAME training (fixed + per-user random effect): entity
    exchange routes each user's samples to its owner process, residual score
    exchanges cross the shared filesystem per coordinate update, and the
    saved model must match the single-process driver run — fixed-effect
    coefficients AND every per-entity random-effect row."""
    import jax.numpy as jnp
    import numpy as np

    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap

    rng = np.random.default_rng(23)
    d, n_users, n = 4, 11, 360
    w_true = rng.normal(size=d)
    u_eff = 1.2 * rng.normal(size=n_users)
    fe_imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    re_imap = IndexMap.build(["bias\x01"], add_intercept=False)
    (tmp_path / "index-maps").mkdir()
    fe_imap.save(str(tmp_path / "index-maps" / "global.npz"))
    re_imap.save(str(tmp_path / "index-maps" / "re.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            u = int(r.integers(0, n_users))
            y = float((x @ w_true + u_eff[u] + 0.3 * r.normal()) > 0)
            yield {
                "uid": f"{seed}-{i}",
                "label": y,
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ] + [{"name": "bias", "term": "", "value": 1.0}],
                "metadataMap": {"userId": f"u{u}"},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(200, seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "in" / "part-b.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(160, seed=2),
    )

    def load(root):
        from photon_ml_tpu.io.model_io import load_game_model

        return load_game_model(
            str(root / "best"), {"global": fe_imap, "per-user": re_imap}
        )

    common = [
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--feature-shard-configurations", "name=re,feature.bags=features",
        "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
        "--training-task", "LOGISTIC_REGRESSION",
        "--coordinate-update-sequence", "global,per-user",
        "--coordinate-configurations",
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=80,"
        "tolerance=1e-9,regularization=L2,reg.weights=1.0",
        "--coordinate-configurations",
        "name=per-user,feature.shard=re,random.effect.type=userId,"
        "optimizer=LBFGS,max.iter=60,tolerance=1e-9,regularization=L2,reg.weights=1.0",
        "--coordinate-descent-iterations", "2",
    ]
    from photon_ml_tpu.cli.game_training_driver import build_arg_parser, run

    run(build_arg_parser().parse_args([
        "--input-data-directories", str(tmp_path / "in"),
        "--root-output-directory", str(tmp_path / "out-single"),
        *common,
    ]))
    ref = load(tmp_path / "out-single")

    port = _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    worker = os.path.join(REPO, "tests", "mp_game_worker.py")
    logs = [open(tmp_path / f"gamer{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(tmp_path)],
            env=env, stdout=logs[i], stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=300)
            assert rc == 0, (
                f"gamer {i} failed:\n" + (tmp_path / f"gamer{i}.log").read_text()
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()

    got = load(tmp_path / "out")
    fe_ref = np.asarray(ref.get_model("global").model.coefficients.means)
    fe_got = np.asarray(got.get_model("global").model.coefficients.means)
    # the in-process reference runs under the suite's x64 config, the workers
    # at f32: agreement is bounded by f32 block-CD drift, not exchange logic
    # (the nproc=1 multi-process path matches the reference EXACTLY)
    np.testing.assert_allclose(fe_got, fe_ref, atol=2e-3)

    re_ref, re_got = ref.get_model("per-user"), got.get_model("per-user")
    assert set(re_got.entity_ids) == set(re_ref.entity_ids) and len(
        re_got.entity_ids
    ) == n_users
    any_nonzero = False
    for eid in re_ref.entity_ids:
        a = re_ref.coefficients_for_entity(eid)
        b = re_got.coefficients_for_entity(eid)
        np.testing.assert_allclose(b, a, atol=2e-3, err_msg=str(eid))
        any_nonzero = any_nonzero or np.abs(a).max() > 1e-3
    assert any_nonzero  # parity of all-zero models would prove nothing


def test_two_process_two_device_training(tmp_path):
    """2 processes x 2 local devices each (the pod shape: several chips per
    host): the global mesh spans 4 devices, per-process padding targets the
    local device count, and the trained model still matches single-process."""
    import numpy as np

    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap

    rng = np.random.default_rng(31)
    d, n = 4, 320
    w_true = rng.normal(size=d)
    imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    (tmp_path / "index-maps").mkdir()
    imap.save(str(tmp_path / "index-maps" / "global.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            yield {
                "uid": f"{seed}-{i}",
                "label": float((x @ w_true + 0.3 * r.normal()) > 0),
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ],
                "metadataMap": {},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    (tmp_path / "val").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(200, seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "in" / "part-b.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(120, seed=2),
    )
    avro_io.write_container(
        str(tmp_path / "val" / "part-0.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(100, seed=5),
    )

    from photon_ml_tpu.cli.game_training_driver import build_arg_parser, run
    from photon_ml_tpu.io.model_io import load_game_model

    run(build_arg_parser().parse_args([
        "--input-data-directories", str(tmp_path / "in"),
        "--validation-data-directories", str(tmp_path / "val"),
        "--root-output-directory", str(tmp_path / "out-single"),
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
        "--training-task", "LOGISTIC_REGRESSION",
        "--coordinate-update-sequence", "global",
        "--coordinate-configurations",
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=100,"
        "tolerance=1e-9,regularization=L2,reg.weights=0.1|10",
        "--evaluators", "AUC",
        "--variance-computation-type", "SIMPLE",
    ]))

    def best_coefficients(root):
        gm = load_game_model(str(root / "best"), {"global": imap})
        return gm.get_model("global").model.coefficients

    def best_coeffs(root):
        return np.asarray(best_coefficients(root).means)

    expected = best_coeffs(tmp_path / "out-single")

    port = _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",  # 2 per process
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    worker = os.path.join(REPO, "tests", "mp_train_worker.py")
    logs = [open(tmp_path / f"pod{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(tmp_path),
             "--variance-computation-type", "SIMPLE"],
            env=env, stdout=logs[i], stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=240)
            assert rc == 0, (
                f"pod {i} failed:\n" + (tmp_path / f"pod{i}.log").read_text()
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()

    got = best_coeffs(tmp_path / "out")
    np.testing.assert_allclose(got, expected, atol=1e-4)
    v_ref = np.asarray(best_coefficients(tmp_path / "out-single").variances)
    v_got = np.asarray(best_coefficients(tmp_path / "out").variances)
    assert (v_got > 0).all()
    np.testing.assert_allclose(v_got, v_ref, rtol=5e-3)


def test_two_process_game_training_single_entity(tmp_path):
    """One entity total: one process owns ALL random-effect work, the other
    owns none — empty owner datasets, empty model parts and empty score
    sends must flow through every exchange without deadlock or error."""
    import numpy as np

    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap

    rng = np.random.default_rng(41)
    d, n = 3, 140
    w_true = rng.normal(size=d)
    fe_imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    re_imap = IndexMap.build(["bias\x01"], add_intercept=False)
    (tmp_path / "index-maps").mkdir()
    fe_imap.save(str(tmp_path / "index-maps" / "global.npz"))
    re_imap.save(str(tmp_path / "index-maps" / "re.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            yield {
                "uid": f"{seed}-{i}",
                "label": float((x @ w_true + 0.8 + 0.3 * r.normal()) > 0),
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ] + [{"name": "bias", "term": "", "value": 1.0}],
                "metadataMap": {"userId": "the-only-user"},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(80, seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "in" / "part-b.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(60, seed=2),
    )

    from photon_ml_tpu.cli.game_training_driver import build_arg_parser, run

    run(build_arg_parser().parse_args([
        "--input-data-directories", str(tmp_path / "in"),
        "--root-output-directory", str(tmp_path / "out-single"),
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--feature-shard-configurations", "name=re,feature.bags=features",
        "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
        "--training-task", "LOGISTIC_REGRESSION",
        "--coordinate-update-sequence", "global,per-user",
        "--coordinate-configurations",
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=80,"
        "tolerance=1e-9,regularization=L2,reg.weights=1.0",
        "--coordinate-configurations",
        "name=per-user,feature.shard=re,random.effect.type=userId,"
        "optimizer=LBFGS,max.iter=60,tolerance=1e-9,regularization=L2,reg.weights=1.0",
        "--coordinate-descent-iterations", "2",
    ]))

    port = _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    worker = os.path.join(REPO, "tests", "mp_game_worker.py")
    logs = [open(tmp_path / f"solo{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(tmp_path)],
            env=env, stdout=logs[i], stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=300)
            assert rc == 0, (
                f"solo {i} failed:\n" + (tmp_path / f"solo{i}.log").read_text()
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()

    from photon_ml_tpu.io.model_io import load_game_model

    def load(root):
        return load_game_model(
            str(root / "best"), {"global": fe_imap, "per-user": re_imap}
        )

    ref, got = load(tmp_path / "out-single"), load(tmp_path / "out")
    np.testing.assert_allclose(
        np.asarray(got.get_model("global").model.coefficients.means),
        np.asarray(ref.get_model("global").model.coefficients.means),
        atol=2e-4,
    )
    assert tuple(got.get_model("per-user").entity_ids) == ("the-only-user",)
    # single-entity bias matches single-process exactly (the FE intercept
    # absorbs the mean shift, so the bias itself may legitimately be ~0)
    np.testing.assert_allclose(
        np.asarray(got.get_model("per-user").coefficients_for_entity("the-only-user")),
        np.asarray(ref.get_model("per-user").coefficients_for_entity("the-only-user")),
        atol=2e-4,
    )

def _entity_coeff_map(model, eid):
    """{global column id: coefficient} for one entity — column-faithful
    comparison (a value-multiset match would hide a permuted exchange)."""
    row = model.row_for_entity(eid)
    proj = np.asarray(model.proj_indices)[row]
    coef = np.asarray(model.coeffs)[row]
    return {int(c): float(v) for c, v in zip(proj, coef) if c >= 0}


def test_two_process_game_training_wide_sparse_re_shard(tmp_path):
    """Random-effect shards wider than the old 4096 dense cap: exchange rows
    travel as COO triples (O(nnz) volume, width-independent), owners
    reassemble CSR — per-entity coefficients still match the single-process
    driver (RandomEffectDataset.scala:46-508's sparse-record shuffle)."""
    import numpy as np

    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap

    rng = np.random.default_rng(31)
    d, n_users, n_wide = 3, 7, 5000
    w_true = rng.normal(size=d)
    u_eff = 1.5 * rng.normal(size=n_users)
    fe_imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    # 5000-wide RE feature space; every sample touches bias + 2 random columns
    re_imap = IndexMap.build(
        ["bias\x01"] + [f"w{j}\x01" for j in range(n_wide - 1)], add_intercept=False
    )
    assert re_imap.size > 4096
    (tmp_path / "index-maps").mkdir()
    fe_imap.save(str(tmp_path / "index-maps" / "global.npz"))
    re_imap.save(str(tmp_path / "index-maps" / "re.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            u = int(r.integers(0, n_users))
            y = float((x @ w_true + u_eff[u] + 0.3 * r.normal()) > 0)
            wide = r.integers(1, n_wide - 1, size=2)
            yield {
                "uid": f"{seed}-{i}",
                "label": y,
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ] + [{"name": "bias", "term": "", "value": 1.0}]
                + [
                    {"name": f"w{int(j)}", "term": "", "value": float(r.normal())}
                    for j in wide
                ],
                "metadataMap": {"userId": f"u{u}"},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(120, seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "in" / "part-b.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(100, seed=2),
    )

    def load(root):
        from photon_ml_tpu.io.model_io import load_game_model

        return load_game_model(
            str(root / "best"), {"global": fe_imap, "per-user": re_imap}
        )

    from photon_ml_tpu.cli.game_training_driver import build_arg_parser, run

    common = [
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--feature-shard-configurations", "name=re,feature.bags=features",
        "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
        "--training-task", "LOGISTIC_REGRESSION",
        "--coordinate-update-sequence", "global,per-user",
        "--coordinate-configurations",
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=80,"
        "tolerance=1e-9,regularization=L2,reg.weights=1.0",
        "--coordinate-configurations",
        "name=per-user,feature.shard=re,random.effect.type=userId,"
        "optimizer=LBFGS,max.iter=60,tolerance=1e-9,regularization=L2,reg.weights=1.0",
        "--coordinate-descent-iterations", "8",
    ]
    run(build_arg_parser().parse_args([
        "--input-data-directories", str(tmp_path / "in"),
        "--root-output-directory", str(tmp_path / "out-single"),
        *common,
    ]))
    ref = load(tmp_path / "out-single")

    port = _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    worker = os.path.join(REPO, "tests", "mp_game_worker.py")
    logs = [open(tmp_path / f"wide{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(tmp_path),
             "--coordinate-descent-iterations", "8"],
            env=env, stdout=logs[i], stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=300)
            assert rc == 0, (
                f"wide {i} failed:\n" + (tmp_path / f"wide{i}.log").read_text()
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()

    got = load(tmp_path / "out")
    np.testing.assert_allclose(
        np.asarray(got.get_model("global").model.coefficients.means),
        np.asarray(ref.get_model("global").model.coefficients.means),
        atol=2e-4,
    )
    re_ref, re_got = ref.get_model("per-user"), got.get_model("per-user")
    assert set(re_got.entity_ids) == set(re_ref.entity_ids)
    for eid in re_ref.entity_ids:
        a = _entity_coeff_map(re_ref, eid)
        b = _entity_coeff_map(re_got, eid)
        assert set(a) == set(b), eid  # same feature columns per entity
        for col in a:
            assert abs(a[col] - b[col]) < 5e-4, (eid, col, a[col], b[col])


def test_two_process_game_validation_selects_best_lambda(tmp_path):
    """Per-update validation tracking in multi-process GAME coordinate
    descent (CoordinateDescent.scala:256-289): the sweep records a validation
    AUC per configuration, best_index = argmax, and the selected
    regularization weight matches the single-process driver's selection."""
    import json as _json

    import numpy as np

    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap

    rng = np.random.default_rng(47)
    d, n_users = 4, 9
    # user effects dominate the signal: killing them (absurd RE lambda)
    # decisively costs AUC, so selection between the sweep's configs is not
    # a numerical coin flip
    w_true = rng.normal(size=d) * 0.5
    u_eff = 2.5 * np.where(rng.random(n_users) > 0.5, 1.0, -1.0)
    fe_imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    re_imap = IndexMap.build(["bias\x01"], add_intercept=False)
    (tmp_path / "index-maps").mkdir()
    fe_imap.save(str(tmp_path / "index-maps" / "global.npz"))
    re_imap.save(str(tmp_path / "index-maps" / "re.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            u = int(r.integers(0, n_users))
            y = float((x @ w_true + u_eff[u] + 0.3 * r.normal()) > 0)
            yield {
                "uid": f"{seed}-{i}",
                "label": y,
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ] + [{"name": "bias", "term": "", "value": 1.0}],
                "metadataMap": {"userId": f"u{u}"},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    (tmp_path / "val").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(180, seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "in" / "part-b.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(140, seed=2),
    )
    avro_io.write_container(
        str(tmp_path / "val" / "part-0.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(120, seed=3),
    )

    # sweep on the RANDOM-EFFECT lambda, absurd weight FIRST: the absurd
    # config trains cold (no warm-start carryover of good models) and loses
    # the dominant user effects, so per-update selection must decisively
    # prefer the sane config
    common = [
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--feature-shard-configurations", "name=re,feature.bags=features",
        "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
        "--training-task", "LOGISTIC_REGRESSION",
        "--coordinate-update-sequence", "global,per-user",
        "--coordinate-configurations",
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=80,"
        "tolerance=1e-9,regularization=L2,reg.weights=1.0",
        "--coordinate-configurations",
        "name=per-user,feature.shard=re,random.effect.type=userId,"
        "optimizer=LBFGS,max.iter=60,tolerance=1e-9,regularization=L2,"
        "reg.weights=100000.0|1.0",
        "--coordinate-descent-iterations", "2",
    ]
    from photon_ml_tpu.cli.game_training_driver import build_arg_parser, run

    run(build_arg_parser().parse_args([
        "--input-data-directories", str(tmp_path / "in"),
        "--validation-data-directories", str(tmp_path / "val"),
        "--root-output-directory", str(tmp_path / "out-single"),
        *common,
    ]))
    from photon_ml_tpu.cli.parsers import parse_coordinate_configuration

    spec_single = _json.loads(
        (tmp_path / "out-single" / "best" / "model-spec.json").read_text()
    )
    _, cfg_single = parse_coordinate_configuration(spec_single["per-user"])
    single_lam = cfg_single.optimization_config.regularization_weight

    port = _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    worker = os.path.join(REPO, "tests", "mp_game_worker.py")
    logs = [open(tmp_path / f"vsel{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [
                sys.executable, worker, str(i), "2", str(port), str(tmp_path),
                "--validation-data-directories", str(tmp_path / "val"),
                # later duplicate coordinate names override the worker's
                # built-in configs: inject the sweep
                "--coordinate-configurations",
                "name=per-user,feature.shard=re,random.effect.type=userId,"
                "optimizer=LBFGS,max.iter=60,tolerance=1e-9,regularization=L2,"
                "reg.weights=100000.0|1.0",
            ],
            env=env, stdout=logs[i], stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=300)
            assert rc == 0, (
                f"vsel {i} failed:\n" + (tmp_path / f"vsel{i}.log").read_text()
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()

    summary = _json.loads((tmp_path / "out" / "summary.json").read_text())
    aucs = [r["auc"] for r in summary["results"]]
    assert all(a is not None for a in aucs)
    assert summary["best_index"] == int(np.argmax(aucs))
    # the absurd-lambda config must lose, matching single-process selection
    best_lam = summary["results"][summary["best_index"]][
        "regularization_weight"]["per-user"]
    assert best_lam == 1.0
    assert best_lam == single_lam


def test_two_process_game_training_random_projection(tmp_path):
    """Random-projection coordinates train multi-process: the projection
    matrix is a pure function of (config seed, dim), so every owner builds
    the identical projector with no cross-process state; saved models export
    through the exact back-projection and must match the single-process
    driver (RandomEffectModelInProjectedSpace.scala:151 semantics)."""
    import numpy as np

    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap

    rng = np.random.default_rng(53)
    d, n_users, n_wide = 3, 6, 600
    w_true = rng.normal(size=d)
    u_eff = 1.5 * rng.normal(size=n_users)
    fe_imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    re_imap = IndexMap.build(
        ["bias\x01"] + [f"w{j}\x01" for j in range(n_wide - 1)], add_intercept=False
    )
    (tmp_path / "index-maps").mkdir()
    fe_imap.save(str(tmp_path / "index-maps" / "global.npz"))
    re_imap.save(str(tmp_path / "index-maps" / "re.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            u = int(r.integers(0, n_users))
            y = float((x @ w_true + u_eff[u] + 0.3 * r.normal()) > 0)
            wide = r.integers(1, n_wide - 1, size=3)
            yield {
                "uid": f"{seed}-{i}",
                "label": y,
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ] + [{"name": "bias", "term": "", "value": 1.0}]
                + [
                    {"name": f"w{int(j)}", "term": "", "value": float(r.normal())}
                    for j in wide
                ],
                "metadataMap": {"userId": f"u{u}"},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(120, seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "in" / "part-b.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(100, seed=2),
    )

    re_coord = (
        "name=per-user,feature.shard=re,random.effect.type=userId,"
        "optimizer=LBFGS,max.iter=60,tolerance=1e-9,regularization=L2,"
        "reg.weights=1.0,projected.dim=4,projection.seed=17"
    )
    common = [
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--feature-shard-configurations", "name=re,feature.bags=features",
        "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
        "--training-task", "LOGISTIC_REGRESSION",
        "--coordinate-update-sequence", "global,per-user",
        "--coordinate-configurations",
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=80,"
        "tolerance=1e-9,regularization=L2,reg.weights=1.0",
        "--coordinate-configurations", re_coord,
        "--coordinate-descent-iterations", "2",
    ]
    from photon_ml_tpu.cli.game_training_driver import build_arg_parser, run

    run(build_arg_parser().parse_args([
        "--input-data-directories", str(tmp_path / "in"),
        "--root-output-directory", str(tmp_path / "out-single"),
        *common,
    ]))

    port = _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    worker = os.path.join(REPO, "tests", "mp_game_worker.py")
    logs = [open(tmp_path / f"proj{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(tmp_path),
             "--coordinate-configurations", re_coord],
            env=env, stdout=logs[i], stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=300)
            assert rc == 0, (
                f"proj {i} failed:\n" + (tmp_path / f"proj{i}.log").read_text()
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()

    from photon_ml_tpu.io.model_io import load_game_model

    def load(root):
        return load_game_model(
            str(root / "best"), {"global": fe_imap, "per-user": re_imap}
        )

    ref, got = load(tmp_path / "out-single"), load(tmp_path / "out")
    np.testing.assert_allclose(
        np.asarray(got.get_model("global").model.coefficients.means),
        np.asarray(ref.get_model("global").model.coefficients.means),
        atol=2e-3,
    )
    re_ref, re_got = ref.get_model("per-user"), got.get_model("per-user")
    assert set(re_got.entity_ids) == set(re_ref.entity_ids)
    any_nonzero = False
    for eid in re_ref.entity_ids:
        a = _entity_coeff_map(re_ref, eid)
        b = _entity_coeff_map(re_got, eid)
        assert set(a) == set(b), eid  # same original-space columns per entity
        for col in a:
            assert abs(a[col] - b[col]) < 2e-3, (eid, col, a[col], b[col])
        any_nonzero = any_nonzero or (a and max(abs(v) for v in a.values()) > 1e-3)
    assert any_nonzero


def test_two_process_linear_training_selects_by_rmse(tmp_path):
    """Regression-task validation selection in the multi-process FE path:
    selection ranks by the task's own metric (min RMSE, ModelSelection.scala:
    30-92) — never AUC over continuous labels. An absurd ridge weight must
    lose to the sane one."""
    import json as _json

    import numpy as np

    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap

    rng = np.random.default_rng(61)
    d = 5
    w_true = rng.normal(size=d) * 2.0
    imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=False)
    (tmp_path / "index-maps").mkdir()
    imap.save(str(tmp_path / "index-maps" / "global.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            yield {
                "uid": f"{seed}-{i}",
                "label": float(x @ w_true + 0.1 * r.normal()),
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ],
                "metadataMap": {},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    (tmp_path / "val").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(160, seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "in" / "part-b.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(140, seed=2),
    )
    avro_io.write_container(
        str(tmp_path / "val" / "part-0.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(120, seed=3),
    )

    port = _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    worker = os.path.join(REPO, "tests", "mp_train_worker.py")
    extra = [
        "--training-task", "LINEAR_REGRESSION",
        "--evaluators", "RMSE",
        "--coordinate-configurations",
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=100,"
        "tolerance=1e-9,regularization=L2,reg.weights=0.1|100000",
    ]
    logs = [open(tmp_path / f"lin{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(tmp_path), *extra],
            env=env, stdout=logs[i], stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=300)
            assert rc == 0, (
                f"lin {i} failed:\n" + (tmp_path / f"lin{i}.log").read_text()
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()

    summary = _json.loads((tmp_path / "out" / "summary.json").read_text())
    rows = summary["results"]
    assert all(r["metric"] == "RMSE" for r in rows)
    assert all(r["auc"] is None for r in rows)  # no AUC-over-continuous lie
    values = [r["value"] for r in rows]
    assert summary["best_index"] == int(np.argmin(values))  # min-RMSE wins
    best = rows[summary["best_index"]]
    assert best["regularization_weight"] == 0.1
    assert best["value"] < min(v for i, v in enumerate(values)
                               if i != summary["best_index"])


def test_two_process_training_with_standardization(tmp_path):
    """Normalized multi-process fixed-effect training: global feature
    statistics assemble from per-process column sums (host allgather), the
    solve runs in transformed space, and the saved original-space model
    matches the single-process driver's standardized fit."""
    import numpy as np

    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap

    rng = np.random.default_rng(71)
    d = 5
    w_true = rng.normal(size=d)
    # wildly different feature scales: normalization materially changes the fit
    scales = np.array([1.0, 50.0, 0.02, 7.0, 300.0])
    imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    (tmp_path / "index-maps").mkdir()
    imap.save(str(tmp_path / "index-maps" / "global.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d) * scales
            y = float((x @ (w_true / scales) + 0.3 * r.normal()) > 0)
            yield {
                "uid": f"{seed}-{i}",
                "label": y,
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ],
                "metadataMap": {},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    (tmp_path / "val").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(180, seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "in" / "part-b.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(140, seed=2),
    )
    avro_io.write_container(
        str(tmp_path / "val" / "part-0.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(100, seed=3),
    )

    from photon_ml_tpu.cli.game_training_driver import build_arg_parser, run
    from photon_ml_tpu.io.model_io import load_game_model

    common_extra = [
        "--normalization", "STANDARDIZATION",
    ]
    run(build_arg_parser().parse_args([
        "--input-data-directories", str(tmp_path / "in"),
        "--validation-data-directories", str(tmp_path / "val"),
        "--root-output-directory", str(tmp_path / "out-single"),
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
        "--training-task", "LOGISTIC_REGRESSION",
        "--coordinate-update-sequence", "global",
        "--coordinate-configurations",
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=100,"
        "tolerance=1e-9,regularization=L2,reg.weights=0.1|10",
        *common_extra,
    ]))
    ref = load_game_model(str(tmp_path / "out-single" / "best"), {"global": imap})

    port = _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    worker = os.path.join(REPO, "tests", "mp_train_worker.py")
    logs = [open(tmp_path / f"norm{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(tmp_path),
             *common_extra],
            env=env, stdout=logs[i], stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=300)
            assert rc == 0, (
                f"norm {i} failed:\n" + (tmp_path / f"norm{i}.log").read_text()
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()

    got = load_game_model(str(tmp_path / "out" / "best"), {"global": imap})
    fe_ref = np.asarray(ref.get_model("global").model.coefficients.means)
    fe_got = np.asarray(got.get_model("global").model.coefficients.means)
    assert np.abs(fe_ref).max() > 1e-3
    # original-space coefficients: relative tolerance (feature scales span
    # 1e4, and the two paths accumulate f32 differently in transformed space)
    np.testing.assert_allclose(fe_got, fe_ref, rtol=5e-3, atol=1e-5)


def test_global_feature_stats_matches_compute():
    """_global_feature_stats (nproc=1 degenerate allgather) must equal
    FeatureDataStatistics.compute exactly on dense AND sparse inputs — the
    multi-process form of MultivariateOnlineSummarizer."""
    import numpy as np
    import scipy.sparse as sp

    from photon_ml_tpu.cli.distributed_training import _global_feature_stats
    from photon_ml_tpu.normalization import FeatureDataStatistics

    class FakeInput:
        def __init__(self, X):
            self._X = X

        def shard(self, s):
            return self._X

    rng = np.random.default_rng(0)
    Xd = rng.normal(size=(137, 6)) * np.array([1, 30, 0.01, 5, 100, 2.0])
    # offset one column so |mean| >> std (the f32-cancellation regime)
    Xd[:, 4] += 5000.0
    Xs = sp.csr_matrix(np.where(np.abs(Xd) > 1.0, Xd, 0.0))
    for name, X in (("dense", Xd), ("sparse", Xs.astype(np.float32))):
        got = _global_feature_stats(FakeInput(X), "s", intercept_index=2)
        # truth at f64: the helper upcasts sums deliberately, so for f32
        # input it is MORE accurate than compute() on the raw f32 matrix
        want = FeatureDataStatistics.compute(
            X.astype(np.float64), intercept_index=2
        )
        for f in ("mean", "variance", "min", "max", "num_nonzeros", "mean_abs"):
            np.testing.assert_allclose(
                getattr(got, f), getattr(want, f), rtol=1e-6, atol=1e-9,
                err_msg=f"{name}.{f}",
            )
        assert got.count == want.count


def test_two_process_game_warm_start_from_model_dir(tmp_path):
    """Model-directory warm start in multi-process GAME training
    (GameTrainingDriver.scala:370-409): every rank loads the saved model,
    owners re-layout random-effect rows via aligned_to, and the warm models'
    scores seed the first residual — a 1-pass warm continuation must match
    the single-process driver's warm continuation."""
    import numpy as np

    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap

    rng = np.random.default_rng(83)
    d, n_users = 3, 7
    w_true = rng.normal(size=d)
    u_eff = 1.4 * rng.normal(size=n_users)
    fe_imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    re_imap = IndexMap.build(["bias\x01"], add_intercept=False)
    (tmp_path / "index-maps").mkdir()
    fe_imap.save(str(tmp_path / "index-maps" / "global.npz"))
    re_imap.save(str(tmp_path / "index-maps" / "re.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            u = int(r.integers(0, n_users))
            y = float((x @ w_true + u_eff[u] + 0.3 * r.normal()) > 0)
            yield {
                "uid": f"{seed}-{i}",
                "label": y,
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ] + [{"name": "bias", "term": "", "value": 1.0}],
                "metadataMap": {"userId": f"u{u}"},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    (tmp_path / "val").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(160, seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "in" / "part-b.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(120, seed=2),
    )
    avro_io.write_container(
        str(tmp_path / "val" / "part-0.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(90, seed=3),
    )

    from photon_ml_tpu.cli.game_training_driver import build_arg_parser, run
    from photon_ml_tpu.io.model_io import load_game_model

    base = [
        "--input-data-directories", str(tmp_path / "in"),
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--feature-shard-configurations", "name=re,feature.bags=features",
        "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
        "--training-task", "LOGISTIC_REGRESSION",
        "--coordinate-update-sequence", "global,per-user",
        "--coordinate-configurations",
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=80,"
        "tolerance=1e-9,regularization=L2,reg.weights=1.0",
        "--coordinate-configurations",
        "name=per-user,feature.shard=re,random.effect.type=userId,"
        "optimizer=LBFGS,max.iter=60,tolerance=1e-9,regularization=L2,reg.weights=1.0",
    ]
    # cold run -> the warm-start source model
    run(build_arg_parser().parse_args([
        *base, "--root-output-directory", str(tmp_path / "cold"),
        "--coordinate-descent-iterations", "1",
    ]))
    warm_dir = str(tmp_path / "cold" / "best")
    # single-process warm continuation
    run(build_arg_parser().parse_args([
        *base, "--root-output-directory", str(tmp_path / "warm-single"),
        "--coordinate-descent-iterations", "1",
        "--model-input-directory", warm_dir,
    ]))
    ref = load_game_model(
        str(tmp_path / "warm-single" / "best"),
        {"global": fe_imap, "per-user": re_imap},
    )

    port = _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    worker = os.path.join(REPO, "tests", "mp_game_worker.py")
    logs = [open(tmp_path / f"warm{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(tmp_path),
             "--coordinate-descent-iterations", "1",
             "--model-input-directory", warm_dir],
            env=env, stdout=logs[i], stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=300)
            assert rc == 0, (
                f"warm {i} failed:\n" + (tmp_path / f"warm{i}.log").read_text()
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()

    got = load_game_model(
        str(tmp_path / "out" / "best"), {"global": fe_imap, "per-user": re_imap}
    )
    np.testing.assert_allclose(
        np.asarray(got.get_model("global").model.coefficients.means),
        np.asarray(ref.get_model("global").model.coefficients.means),
        atol=2e-3,
    )
    re_ref, re_got = ref.get_model("per-user"), got.get_model("per-user")
    assert set(re_got.entity_ids) == set(re_ref.entity_ids)
    any_nonzero = False
    for eid in re_ref.entity_ids:
        a = _entity_coeff_map(re_ref, eid)
        b = _entity_coeff_map(re_got, eid)
        assert set(a) == set(b), eid
        for col in a:
            assert abs(a[col] - b[col]) < 2e-3, (eid, col, a[col], b[col])
        any_nonzero = any_nonzero or (a and max(abs(v) for v in a.values()) > 1e-3)
    assert any_nonzero

    # second warm continuation WITH validation: per-update tracking may
    # snapshot the warm models before any RE update — the saved model must
    # still hold every entity exactly ONCE (owner-local warm rows; a full
    # warm copy on each rank would save each entity nproc times). Selection
    # may legitimately pick a different snapshot than single-process here,
    # so only structure is asserted.
    import shutil

    shutil.rmtree(tmp_path / "out", ignore_errors=True)
    port = _free_port()
    logs = [open(tmp_path / f"warmv{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(tmp_path),
             "--coordinate-descent-iterations", "1",
             "--model-input-directory", warm_dir,
             "--validation-data-directories", str(tmp_path / "val")],
            env=env, stdout=logs[i], stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=300)
            assert rc == 0, (
                f"warmv {i} failed:\n" + (tmp_path / f"warmv{i}.log").read_text()
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()
    got_v = load_game_model(
        str(tmp_path / "out" / "best"), {"global": fe_imap, "per-user": re_imap}
    )
    ids_v = got_v.get_model("per-user").entity_ids
    assert len(ids_v) == len(set(ids_v)) == n_users


def test_two_process_game_training_with_standardization(tmp_path):
    """Normalized multi-process GAME training: every shard's normalization
    context builds from GLOBAL statistics (per-process column-sum allgather
    over home rows), random-effect blocks fold the context per bucket with
    models staying in original space, and the saved model matches the
    single-process standardized run."""
    import numpy as np

    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap

    rng = np.random.default_rng(97)
    d, n_users = 3, 8
    w_scales = np.array([1.0, 40.0, 0.05])
    w_true = rng.normal(size=d)
    u_eff = 1.3 * rng.normal(size=n_users)
    fe_imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    # STANDARDIZATION requires an intercept in every normalized shard; the
    # re shard's intercept column doubles as the per-entity bias
    re_imap = IndexMap.build(["rx\x01"], add_intercept=True)
    (tmp_path / "index-maps").mkdir()
    fe_imap.save(str(tmp_path / "index-maps" / "global.npz"))
    re_imap.save(str(tmp_path / "index-maps" / "re.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d) * w_scales
            u = int(r.integers(0, n_users))
            rx = r.normal() * 25.0  # wildly-scaled per-entity covariate
            y = float(
                (x @ (w_true / w_scales) + u_eff[u] + 0.02 * rx + 0.3 * r.normal())
                > 0
            )
            yield {
                "uid": f"{seed}-{i}",
                "label": y,
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ] + [
                    {"name": "rx", "term": "", "value": float(rx)},
                ],
                "metadataMap": {"userId": f"u{u}"},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(170, seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "in" / "part-b.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(150, seed=2),
    )

    from photon_ml_tpu.cli.game_training_driver import build_arg_parser, run
    from photon_ml_tpu.io.model_io import load_game_model

    common = [
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--feature-shard-configurations", "name=re,feature.bags=features",
        "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
        "--training-task", "LOGISTIC_REGRESSION",
        "--coordinate-update-sequence", "global,per-user",
        "--coordinate-configurations",
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=80,"
        "tolerance=1e-9,regularization=L2,reg.weights=1.0",
        "--coordinate-configurations",
        "name=per-user,feature.shard=re,random.effect.type=userId,"
        "optimizer=LBFGS,max.iter=60,tolerance=1e-9,regularization=L2,reg.weights=1.0",
        "--coordinate-descent-iterations", "2",
        "--normalization", "STANDARDIZATION",
    ]
    run(build_arg_parser().parse_args([
        "--input-data-directories", str(tmp_path / "in"),
        "--root-output-directory", str(tmp_path / "out-single"),
        *common,
    ]))

    port = _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    worker = os.path.join(REPO, "tests", "mp_game_worker.py")
    logs = [open(tmp_path / f"gnorm{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(tmp_path),
             "--normalization", "STANDARDIZATION"],
            env=env, stdout=logs[i], stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=300)
            assert rc == 0, (
                f"gnorm {i} failed:\n" + (tmp_path / f"gnorm{i}.log").read_text()
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()

    def load(root):
        return load_game_model(
            str(root / "best"), {"global": fe_imap, "per-user": re_imap}
        )

    ref, got = load(tmp_path / "out-single"), load(tmp_path / "out")
    fe_ref = np.asarray(ref.get_model("global").model.coefficients.means)
    fe_got = np.asarray(got.get_model("global").model.coefficients.means)
    assert np.abs(fe_ref).max() > 1e-3
    np.testing.assert_allclose(fe_got, fe_ref, rtol=5e-3, atol=1e-5)
    re_ref, re_got = ref.get_model("per-user"), got.get_model("per-user")
    assert set(re_got.entity_ids) == set(re_ref.entity_ids)
    any_nonzero = False
    for eid in re_ref.entity_ids:
        a = _entity_coeff_map(re_ref, eid)
        b = _entity_coeff_map(re_got, eid)
        assert set(a) == set(b), eid
        for col in a:
            assert abs(a[col] - b[col]) <= max(5e-3 * abs(a[col]), 2e-3), (
                eid, col, a[col], b[col],
            )
        any_nonzero = any_nonzero or (a and max(abs(v) for v in a.values()) > 1e-3)
    assert any_nonzero


def test_multiprocess_output_mode_all_and_none(tmp_path):
    """--output-mode ALL writes models/<i>/ per swept configuration alongside
    best/ (GameTrainingDriver.scala:759-826); NONE writes no model but still
    records summary.json. Exercised through the library runner at nproc=1
    (same code path; shuffle barriers no-op)."""
    import json as _json

    import numpy as np

    from photon_ml_tpu.cli.distributed_training import run_multiprocess_game
    from photon_ml_tpu.cli.game_training_driver import (
        _load_index_maps,
        build_arg_parser,
    )
    from photon_ml_tpu.cli.parsers import (
        parse_coordinate_configuration,
        parse_feature_shard_configuration,
    )
    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap
    from photon_ml_tpu.types import TaskType
    from photon_ml_tpu.util import PhotonLogger

    rng = np.random.default_rng(29)
    d, n_users = 3, 5
    w_true = rng.normal(size=d)
    u_eff = 1.5 * rng.normal(size=n_users)
    fe_imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    re_imap = IndexMap.build(["bias\x01"], add_intercept=False)
    (tmp_path / "index-maps").mkdir()
    fe_imap.save(str(tmp_path / "index-maps" / "global.npz"))
    re_imap.save(str(tmp_path / "index-maps" / "re.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            u = int(r.integers(0, n_users))
            y = float((x @ w_true + u_eff[u] + 0.3 * r.normal()) > 0)
            yield {
                "uid": f"{seed}-{i}",
                "label": y,
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ] + [{"name": "bias", "term": "", "value": 1.0}],
                "metadataMap": {"userId": f"u{u}"},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(150, seed=1),
    )

    def run_mode(mode, out):
        args = build_arg_parser().parse_args([
            "--input-data-directories", str(tmp_path / "in"),
            "--root-output-directory", str(out),
            "--feature-shard-configurations", "name=global,feature.bags=features",
            "--feature-shard-configurations", "name=re,feature.bags=features",
            "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
            "--training-task", "LOGISTIC_REGRESSION",
            "--coordinate-update-sequence", "global,per-user",
            "--coordinate-configurations",
            "name=global,feature.shard=global,optimizer=LBFGS,max.iter=60,"
            "tolerance=1e-9,regularization=L2,reg.weights=0.1|10",
            "--coordinate-configurations",
            "name=per-user,feature.shard=re,random.effect.type=userId,"
            "optimizer=LBFGS,max.iter=40,tolerance=1e-9,regularization=L2,"
            "reg.weights=1.0",
            "--coordinate-descent-iterations", "1",
            "--output-mode", mode,
        ])
        shard_configs = dict(
            parse_feature_shard_configuration(a)
            for a in args.feature_shard_configurations
        )
        coord_configs = dict(
            parse_coordinate_configuration(a) for a in args.coordinate_configurations
        )
        os.makedirs(out, exist_ok=True)
        run_multiprocess_game(
            args, 0, 1, PhotonLogger(str(out / "log.txt")), str(out),
            TaskType("LOGISTIC_REGRESSION"), coord_configs, shard_configs,
            _load_index_maps(args.off_heap_index_map_directory, shard_configs),
        )

    run_mode("ALL", tmp_path / "all")
    assert (tmp_path / "all" / "best").is_dir()
    for i in (0, 1):
        spec = _json.loads(
            (tmp_path / "all" / "models" / str(i) / "model-spec.json").read_text()
        )
        assert "global" in spec and "per-user" in spec
    # the two configs differ by reg weight in their recorded specs
    s0 = (tmp_path / "all" / "models" / "0" / "model-spec.json").read_text()
    s1 = (tmp_path / "all" / "models" / "1" / "model-spec.json").read_text()
    assert s0 != s1

    run_mode("NONE", tmp_path / "none")
    assert not (tmp_path / "none" / "best").exists()
    assert (tmp_path / "none" / "summary.json").exists()


def test_multiprocess_fe_output_mode_all_and_none(tmp_path):
    """The fixed-effect-only runner's ALL/NONE branches: models/<i>/ per
    swept lambda, and NONE leaving only summary.json."""
    import json as _json

    import numpy as np

    from photon_ml_tpu.cli.distributed_training import run_multiprocess_fixed_effect
    from photon_ml_tpu.cli.game_training_driver import (
        _load_index_maps,
        build_arg_parser,
    )
    from photon_ml_tpu.cli.parsers import (
        parse_coordinate_configuration,
        parse_feature_shard_configuration,
    )
    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap
    from photon_ml_tpu.types import TaskType
    from photon_ml_tpu.util import PhotonLogger

    rng = np.random.default_rng(43)
    d = 4
    w_true = rng.normal(size=d)
    imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=False)
    (tmp_path / "index-maps").mkdir()
    imap.save(str(tmp_path / "index-maps" / "global.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            yield {
                "uid": f"{seed}-{i}",
                "label": float((x @ w_true + 0.3 * r.normal()) > 0),
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ],
                "metadataMap": {},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(120, seed=1),
    )

    def run_mode(mode, out):
        args = build_arg_parser().parse_args([
            "--input-data-directories", str(tmp_path / "in"),
            "--root-output-directory", str(out),
            "--feature-shard-configurations", "name=global,feature.bags=features",
            "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
            "--training-task", "LOGISTIC_REGRESSION",
            "--coordinate-update-sequence", "global",
            "--coordinate-configurations",
            "name=global,feature.shard=global,optimizer=LBFGS,max.iter=60,"
            "tolerance=1e-9,regularization=L2,reg.weights=0.1|10",
            "--output-mode", mode,
        ])
        shard_configs = dict(
            parse_feature_shard_configuration(a)
            for a in args.feature_shard_configurations
        )
        coord_configs = dict(
            parse_coordinate_configuration(a) for a in args.coordinate_configurations
        )
        os.makedirs(out, exist_ok=True)
        run_multiprocess_fixed_effect(
            args, 0, 1, PhotonLogger(str(out / "log.txt")), str(out),
            TaskType("LOGISTIC_REGRESSION"), coord_configs, shard_configs,
            _load_index_maps(args.off_heap_index_map_directory, shard_configs),
        )

    run_mode("ALL", tmp_path / "all")
    assert (tmp_path / "all" / "best").is_dir()
    specs = set()
    for i in (0, 1):
        spec = _json.loads(
            (tmp_path / "all" / "models" / str(i) / "model-spec.json").read_text()
        )
        specs.add(spec["global"])
    assert len(specs) == 2  # distinct reg weights recorded per config

    run_mode("NONE", tmp_path / "none")
    assert not (tmp_path / "none" / "best").exists()
    assert (tmp_path / "none" / "summary.json").exists()


def test_two_process_game_partial_retrain_locked_coordinate(tmp_path):
    """Partial retrain in multi-process GAME: the locked fixed effect keeps
    its loaded coefficients EXACTLY (scored every pass, never re-optimized —
    ModelCoordinate semantics, CoordinateDescent.scala:45) while the
    random-effect coordinate retrains; parity with the single-process
    driver's partial retrain."""
    import numpy as np

    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap

    rng = np.random.default_rng(101)
    d, n_users = 3, 6
    w_true = rng.normal(size=d)
    u_eff = 1.5 * rng.normal(size=n_users)
    fe_imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    re_imap = IndexMap.build(["bias\x01"], add_intercept=False)
    (tmp_path / "index-maps").mkdir()
    fe_imap.save(str(tmp_path / "index-maps" / "global.npz"))
    re_imap.save(str(tmp_path / "index-maps" / "re.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            u = int(r.integers(0, n_users))
            y = float((x @ w_true + u_eff[u] + 0.3 * r.normal()) > 0)
            yield {
                "uid": f"{seed}-{i}",
                "label": y,
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ] + [{"name": "bias", "term": "", "value": 1.0}],
                "metadataMap": {"userId": f"u{u}"},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(150, seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "in" / "part-b.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(130, seed=2),
    )

    from photon_ml_tpu.cli.game_training_driver import build_arg_parser, run
    from photon_ml_tpu.io.model_io import load_game_model

    base = [
        "--input-data-directories", str(tmp_path / "in"),
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--feature-shard-configurations", "name=re,feature.bags=features",
        "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
        "--training-task", "LOGISTIC_REGRESSION",
        "--coordinate-update-sequence", "global,per-user",
        "--coordinate-configurations",
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=80,"
        "tolerance=1e-9,regularization=L2,reg.weights=1.0",
        "--coordinate-configurations",
        "name=per-user,feature.shard=re,random.effect.type=userId,"
        "optimizer=LBFGS,max.iter=60,tolerance=1e-9,regularization=L2,reg.weights=1.0",
        "--coordinate-descent-iterations", "2",
    ]
    run(build_arg_parser().parse_args([
        *base, "--root-output-directory", str(tmp_path / "full"),
    ]))
    model_dir = str(tmp_path / "full" / "best")

    retrain = [
        "--model-input-directory", model_dir,
        "--partial-retrain-locked-coordinates", "global",
        # retrain the random effect under a DIFFERENT reg weight
        "--coordinate-configurations",
        "name=per-user,feature.shard=re,random.effect.type=userId,"
        "optimizer=LBFGS,max.iter=60,tolerance=1e-9,regularization=L2,reg.weights=5.0",
    ]
    run(build_arg_parser().parse_args([
        *base, *retrain, "--root-output-directory", str(tmp_path / "re-single"),
    ]))

    port = _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    worker = os.path.join(REPO, "tests", "mp_game_worker.py")
    logs = [open(tmp_path / f"lock{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(tmp_path), *retrain],
            env=env, stdout=logs[i], stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=300)
            assert rc == 0, (
                f"lock {i} failed:\n" + (tmp_path / f"lock{i}.log").read_text()
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()

    def load(root):
        return load_game_model(
            str(root / "best"), {"global": fe_imap, "per-user": re_imap}
        )

    src = load(tmp_path / "full")
    ref = load(tmp_path / "re-single")
    got = load(tmp_path / "out")
    fe_src = np.asarray(src.get_model("global").model.coefficients.means)
    fe_got = np.asarray(got.get_model("global").model.coefficients.means)
    # the locked coordinate is byte-identical to the input model
    np.testing.assert_array_equal(fe_got, fe_src)
    np.testing.assert_array_equal(
        fe_got,
        np.asarray(ref.get_model("global").model.coefficients.means),
    )
    # the retrained coordinate moved (different reg weight) and matches
    # single-process partial retrain
    re_src, re_ref, re_got = (
        m.get_model("per-user") for m in (src, ref, got)
    )
    assert set(re_got.entity_ids) == set(re_ref.entity_ids)
    moved = False
    for eid in re_ref.entity_ids:
        a = _entity_coeff_map(re_ref, eid)
        b = _entity_coeff_map(re_got, eid)
        assert set(a) == set(b), eid
        for col in a:
            assert abs(a[col] - b[col]) < 2e-3, (eid, col, a[col], b[col])
        s_ = _entity_coeff_map(re_src, eid)
        moved = moved or any(abs(s_[c] - a[c]) > 1e-3 for c in a)
    assert moved  # stronger reg actually changed the random effects


def test_locked_random_effect_passes_through_verbatim(tmp_path):
    """A LOCKED random-effect coordinate keeps entities that have NO rows in
    the retrain data (ModelCoordinate passes the loaded model through
    verbatim; truncating to the new data's entity set would silently lose
    coefficients)."""
    import numpy as np

    from photon_ml_tpu.cli.distributed_training import run_multiprocess_game
    from photon_ml_tpu.cli.game_training_driver import (
        _load_index_maps,
        build_arg_parser,
        run,
    )
    from photon_ml_tpu.cli.parsers import (
        parse_coordinate_configuration,
        parse_feature_shard_configuration,
    )
    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap
    from photon_ml_tpu.io.model_io import load_game_model
    from photon_ml_tpu.types import TaskType
    from photon_ml_tpu.util import PhotonLogger

    rng = np.random.default_rng(107)
    d, n_users = 3, 6
    w_true = rng.normal(size=d)
    u_eff = 1.5 * rng.normal(size=n_users)
    fe_imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    re_imap = IndexMap.build(["bias\x01"], add_intercept=False)
    (tmp_path / "index-maps").mkdir()
    fe_imap.save(str(tmp_path / "index-maps" / "global.npz"))
    re_imap.save(str(tmp_path / "index-maps" / "re.npz"))

    def records(n_rows, seed, users):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            u = int(users[int(r.integers(0, len(users)))])
            y = float((x @ w_true + u_eff[u] + 0.3 * r.normal()) > 0)
            yield {
                "uid": f"{seed}-{i}",
                "label": y,
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ] + [{"name": "bias", "term": "", "value": 1.0}],
                "metadataMap": {"userId": f"u{u}"},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in-full").mkdir()
    (tmp_path / "in-sub").mkdir()
    avro_io.write_container(
        str(tmp_path / "in-full" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(200, 1, list(range(n_users))),
    )
    # retrain data covers only HALF the users
    avro_io.write_container(
        str(tmp_path / "in-sub" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(120, 2, [0, 1, 2]),
    )

    base = [
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--feature-shard-configurations", "name=re,feature.bags=features",
        "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
        "--training-task", "LOGISTIC_REGRESSION",
        "--coordinate-update-sequence", "global,per-user",
        "--coordinate-configurations",
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=60,"
        "tolerance=1e-9,regularization=L2,reg.weights=1.0",
        "--coordinate-configurations",
        "name=per-user,feature.shard=re,random.effect.type=userId,"
        "optimizer=LBFGS,max.iter=40,tolerance=1e-9,regularization=L2,reg.weights=1.0",
        "--coordinate-descent-iterations", "1",
    ]
    run(build_arg_parser().parse_args([
        *base,
        "--input-data-directories", str(tmp_path / "in-full"),
        "--root-output-directory", str(tmp_path / "src"),
    ]))
    src = load_game_model(
        str(tmp_path / "src" / "best"), {"global": fe_imap, "per-user": re_imap}
    )
    assert len(src.get_model("per-user").entity_ids) == n_users

    args = build_arg_parser().parse_args([
        *base,
        "--input-data-directories", str(tmp_path / "in-sub"),
        "--root-output-directory", str(tmp_path / "out"),
        "--model-input-directory", str(tmp_path / "src" / "best"),
        "--partial-retrain-locked-coordinates", "per-user",
    ])
    shard_configs = dict(
        parse_feature_shard_configuration(a)
        for a in args.feature_shard_configurations
    )
    coord_configs = dict(
        parse_coordinate_configuration(a) for a in args.coordinate_configurations
    )
    os.makedirs(tmp_path / "out", exist_ok=True)
    run_multiprocess_game(
        args, 0, 1, PhotonLogger(str(tmp_path / "out" / "log.txt")),
        str(tmp_path / "out"),
        TaskType("LOGISTIC_REGRESSION"), coord_configs, shard_configs,
        _load_index_maps(args.off_heap_index_map_directory, shard_configs),
    )
    got = load_game_model(
        str(tmp_path / "out" / "best"), {"global": fe_imap, "per-user": re_imap}
    )
    re_src, re_got = src.get_model("per-user"), got.get_model("per-user")
    # ALL six entities survive — including u3/u4/u5 with zero retrain rows —
    # with coefficients exactly equal to the input model's
    assert set(re_got.entity_ids) == set(re_src.entity_ids)
    for eid in re_src.entity_ids:
        np.testing.assert_array_equal(
            re_got.coefficients_for_entity(eid),
            re_src.coefficients_for_entity(eid),
            err_msg=str(eid),
        )


def test_multiprocess_fe_variances_match_single_process(tmp_path):
    """SIMPLE and FULL coefficient variances through the multi-process
    fixed-effect path (psum'd Hessian pass over the sharded data) must match
    the single-process driver's saved variances, including the delta-method
    scaling under STANDARDIZATION."""
    import numpy as np

    from photon_ml_tpu.cli.distributed_training import run_multiprocess_fixed_effect
    from photon_ml_tpu.cli.game_training_driver import (
        _load_index_maps,
        build_arg_parser,
        run,
    )
    from photon_ml_tpu.cli.parsers import (
        parse_coordinate_configuration,
        parse_feature_shard_configuration,
    )
    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap
    from photon_ml_tpu.io.model_io import load_game_model
    from photon_ml_tpu.types import TaskType
    from photon_ml_tpu.util import PhotonLogger

    rng = np.random.default_rng(113)
    d = 4
    w_true = rng.normal(size=d)
    imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    (tmp_path / "index-maps").mkdir()
    imap.save(str(tmp_path / "index-maps" / "global.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d) * np.array([1.0, 20.0, 0.2, 5.0])
            yield {
                "uid": f"{seed}-{i}",
                "label": float((x @ (w_true / np.array([1.0, 20.0, 0.2, 5.0]))
                                + 0.3 * r.normal()) > 0),
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ],
                "metadataMap": {},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(250, seed=1),
    )

    for vtype in ("SIMPLE", "FULL"):
        base = [
            "--input-data-directories", str(tmp_path / "in"),
            "--feature-shard-configurations", "name=global,feature.bags=features",
            "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
            "--training-task", "LOGISTIC_REGRESSION",
            "--coordinate-update-sequence", "global",
            "--coordinate-configurations",
            "name=global,feature.shard=global,optimizer=LBFGS,max.iter=100,"
            "tolerance=1e-9,regularization=L2,reg.weights=1.0",
            "--normalization", "STANDARDIZATION",
            "--variance-computation-type", vtype,
        ]
        run(build_arg_parser().parse_args([
            *base, "--root-output-directory", str(tmp_path / f"single-{vtype}"),
        ]))
        ref = load_game_model(
            str(tmp_path / f"single-{vtype}" / "best"), {"global": imap}
        ).get_model("global").model.coefficients

        args = build_arg_parser().parse_args([
            *base, "--root-output-directory", str(tmp_path / f"mp-{vtype}"),
        ])
        shard_configs = dict(
            parse_feature_shard_configuration(a)
            for a in args.feature_shard_configurations
        )
        coord_configs = dict(
            parse_coordinate_configuration(a) for a in args.coordinate_configurations
        )
        os.makedirs(tmp_path / f"mp-{vtype}", exist_ok=True)
        run_multiprocess_fixed_effect(
            args, 0, 1,
            PhotonLogger(str(tmp_path / f"mp-{vtype}" / "log.txt")),
            str(tmp_path / f"mp-{vtype}"),
            TaskType("LOGISTIC_REGRESSION"), coord_configs, shard_configs,
            _load_index_maps(args.off_heap_index_map_directory, shard_configs),
        )
        got = load_game_model(
            str(tmp_path / f"mp-{vtype}" / "best"), {"global": imap}
        ).get_model("global").model.coefficients
        assert got.variances is not None and ref.variances is not None
        v_ref = np.asarray(ref.variances)
        v_got = np.asarray(got.variances)
        assert (v_got > 0).all()
        np.testing.assert_allclose(v_got, v_ref, rtol=5e-3, err_msg=vtype)


def test_two_process_game_variances_match_single_process(tmp_path):
    """Per-entity (GAME) coefficient variances through the multi-process
    path: owners compute them inside their bucket solves, parts carry them,
    and both the fixed-effect and per-entity variances in the saved model
    match the single-process driver."""
    import numpy as np

    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap

    rng = np.random.default_rng(131)
    d, n_users = 3, 7
    w_true = rng.normal(size=d)
    u_eff = 1.4 * rng.normal(size=n_users)
    fe_imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    re_imap = IndexMap.build(["bias\x01"], add_intercept=False)
    (tmp_path / "index-maps").mkdir()
    fe_imap.save(str(tmp_path / "index-maps" / "global.npz"))
    re_imap.save(str(tmp_path / "index-maps" / "re.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            u = int(r.integers(0, n_users))
            y = float((x @ w_true + u_eff[u] + 0.3 * r.normal()) > 0)
            yield {
                "uid": f"{seed}-{i}",
                "label": y,
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ] + [{"name": "bias", "term": "", "value": 1.0}],
                "metadataMap": {"userId": f"u{u}"},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(160, seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "in" / "part-b.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(140, seed=2),
    )

    from photon_ml_tpu.cli.game_training_driver import build_arg_parser, run
    from photon_ml_tpu.io.model_io import load_game_model

    common = [
        "--input-data-directories", str(tmp_path / "in"),
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--feature-shard-configurations", "name=re,feature.bags=features",
        "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
        "--training-task", "LOGISTIC_REGRESSION",
        "--coordinate-update-sequence", "global,per-user",
        "--coordinate-configurations",
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=80,"
        "tolerance=1e-9,regularization=L2,reg.weights=1.0",
        "--coordinate-configurations",
        "name=per-user,feature.shard=re,random.effect.type=userId,"
        "optimizer=LBFGS,max.iter=60,tolerance=1e-9,regularization=L2,reg.weights=1.0",
        "--coordinate-descent-iterations", "2",
        "--variance-computation-type", "SIMPLE",
    ]
    run(build_arg_parser().parse_args([
        *common, "--root-output-directory", str(tmp_path / "out-single"),
    ]))

    port = _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    worker = os.path.join(REPO, "tests", "mp_game_worker.py")
    logs = [open(tmp_path / f"gvar{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(tmp_path),
             "--variance-computation-type", "SIMPLE"],
            env=env, stdout=logs[i], stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=300)
            assert rc == 0, (
                f"gvar {i} failed:\n" + (tmp_path / f"gvar{i}.log").read_text()
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()

    def load(root):
        return load_game_model(
            str(root / "best"), {"global": fe_imap, "per-user": re_imap}
        )

    ref, got = load(tmp_path / "out-single"), load(tmp_path / "out")
    c_ref = ref.get_model("global").model.coefficients
    c_got = got.get_model("global").model.coefficients
    assert c_got.variances is not None and c_ref.variances is not None
    np.testing.assert_allclose(
        np.asarray(c_got.variances), np.asarray(c_ref.variances), rtol=5e-3
    )
    re_ref, re_got = ref.get_model("per-user"), got.get_model("per-user")
    assert re_got.variances is not None and re_ref.variances is not None
    checked = 0
    for eid in re_ref.entity_ids:
        r_row = re_ref.row_for_entity(eid)
        g_row = re_got.row_for_entity(eid)
        v_ref = np.asarray(re_ref.variances)[r_row]
        v_got = np.asarray(re_got.variances)[g_row]
        assert (v_got[v_ref > 0] > 0).all()
        np.testing.assert_allclose(v_got, v_ref, rtol=1e-2, err_msg=str(eid))
        checked += 1
    assert checked == n_users


def test_two_process_grouped_evaluator_selection(tmp_path):
    """Custom evaluators in multi-process selection: --evaluators AUC:userId
    ranks the sweep by per-group AUC (MultiEvaluator gathered with hashed
    group keys), matching the single-process driver's selection and
    recording every evaluator's value per configuration."""
    import json as _json

    import numpy as np

    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap

    rng = np.random.default_rng(151)
    d, n_groups = 4, 9
    w_true = rng.normal(size=d)
    imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    (tmp_path / "index-maps").mkdir()
    imap.save(str(tmp_path / "index-maps" / "global.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            g = int(r.integers(0, n_groups))
            y = float((x @ w_true + 0.4 * r.normal()) > 0)
            yield {
                "uid": f"{seed}-{i}",
                "label": y,
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ],
                "metadataMap": {"userId": f"u{g}"},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    (tmp_path / "val").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(170, seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "in" / "part-b.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(150, seed=2),
    )
    avro_io.write_container(
        str(tmp_path / "val" / "part-0.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(140, seed=3),
    )

    from photon_ml_tpu.cli.game_training_driver import build_arg_parser, run

    run(build_arg_parser().parse_args([
        "--input-data-directories", str(tmp_path / "in"),
        "--validation-data-directories", str(tmp_path / "val"),
        "--root-output-directory", str(tmp_path / "out-single"),
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
        "--training-task", "LOGISTIC_REGRESSION",
        "--coordinate-update-sequence", "global",
        "--coordinate-configurations",
        # L1: the absurd weight zeroes the model entirely (constant scores,
        # per-group AUC 0.5) so selection cannot coin-flip on shrinkage-
        # invariant rankings
        "name=global,feature.shard=global,optimizer=OWLQN,max.iter=100,"
        "tolerance=1e-9,regularization=L1,reg.weights=0.1|100000",
        "--evaluators", "AUC:userId",
    ]))
    import json

    spec_single = json.loads(
        (tmp_path / "out-single" / "best" / "model-spec.json").read_text()
    )
    from photon_ml_tpu.cli.parsers import parse_coordinate_configuration

    _, cfg_single = parse_coordinate_configuration(spec_single["global"])
    single_lam = cfg_single.optimization_config.regularization_weight

    port = _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    worker = os.path.join(REPO, "tests", "mp_train_worker.py")
    logs = [open(tmp_path / f"gsel{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(tmp_path),
             "--evaluators", "AUC:userId",
             "--coordinate-configurations",
             "name=global,feature.shard=global,optimizer=OWLQN,max.iter=100,"
             "tolerance=1e-9,regularization=L1,reg.weights=0.1|100000"],
            env=env, stdout=logs[i], stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=300)
            assert rc == 0, (
                f"gsel {i} failed:\n" + (tmp_path / f"gsel{i}.log").read_text()
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()

    summary = _json.loads((tmp_path / "out" / "summary.json").read_text())
    rows = summary["results"]
    assert all(r["metric"] == "AUC@userId" for r in rows)
    assert all("AUC@userId" in r["evaluations"] for r in rows)
    values = [r["value"] for r in rows]
    assert summary["best_index"] == int(np.argmax(values))
    best_lam = rows[summary["best_index"]]["regularization_weight"]
    assert best_lam == 0.1 == single_lam  # absurd ridge loses per-group AUC


def test_multiprocess_game_checkpoint_resume_bit_identical(tmp_path):
    """Iteration checkpoint/resume in the multi-process GAME sweep: killing
    the job after any checkpointed pass and re-running with the same
    directory reproduces the uninterrupted run's saved model EXACTLY.
    Simulated by promoting each rank's previous checkpoint generation (the
    state one pass before the end) and re-running."""
    import shutil

    import numpy as np

    from photon_ml_tpu.cli.distributed_training import (
        _mp_ckpt_paths,
        run_multiprocess_game,
    )
    from photon_ml_tpu.cli.game_training_driver import (
        _load_index_maps,
        build_arg_parser,
    )
    from photon_ml_tpu.cli.parsers import (
        parse_coordinate_configuration,
        parse_feature_shard_configuration,
    )
    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap
    from photon_ml_tpu.io.model_io import load_game_model
    from photon_ml_tpu.types import TaskType
    from photon_ml_tpu.util import PhotonLogger

    rng = np.random.default_rng(163)
    d, n_users = 3, 6
    w_true = rng.normal(size=d)
    u_eff = 1.4 * rng.normal(size=n_users)
    fe_imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    re_imap = IndexMap.build(["bias\x01"], add_intercept=False)
    (tmp_path / "index-maps").mkdir()
    fe_imap.save(str(tmp_path / "index-maps" / "global.npz"))
    re_imap.save(str(tmp_path / "index-maps" / "re.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            u = int(r.integers(0, n_users))
            y = float((x @ w_true + u_eff[u] + 0.3 * r.normal()) > 0)
            yield {
                "uid": f"{seed}-{i}",
                "label": y,
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ] + [{"name": "bias", "term": "", "value": 1.0}],
                "metadataMap": {"userId": f"u{u}"},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(180, seed=1),
    )

    def make_args(out, ckpt):
        return build_arg_parser().parse_args([
            "--input-data-directories", str(tmp_path / "in"),
            "--root-output-directory", str(out),
            "--feature-shard-configurations", "name=global,feature.bags=features",
            "--feature-shard-configurations", "name=re,feature.bags=features",
            "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
            "--training-task", "LOGISTIC_REGRESSION",
            "--coordinate-update-sequence", "global,per-user",
            "--coordinate-configurations",
            "name=global,feature.shard=global,optimizer=LBFGS,max.iter=60,"
            "tolerance=1e-9,regularization=L2,reg.weights=0.3|3",
            "--coordinate-configurations",
            "name=per-user,feature.shard=re,random.effect.type=userId,"
            "optimizer=LBFGS,max.iter=40,tolerance=1e-9,regularization=L2,"
            "reg.weights=1.0",
            "--coordinate-descent-iterations", "2",
            "--checkpoint-directory", str(ckpt),
        ])

    def run_one(out, ckpt):
        args = make_args(out, ckpt)
        shard_configs = dict(
            parse_feature_shard_configuration(a)
            for a in args.feature_shard_configurations
        )
        coord_configs = dict(
            parse_coordinate_configuration(a) for a in args.coordinate_configurations
        )
        os.makedirs(out, exist_ok=True)
        run_multiprocess_game(
            args, 0, 1, PhotonLogger(str(out / "log.txt")), str(out),
            TaskType("LOGISTIC_REGRESSION"), coord_configs, shard_configs,
            _load_index_maps(args.off_heap_index_map_directory, shard_configs),
        )
        return load_game_model(
            str(out / "best"), {"global": fe_imap, "per-user": re_imap}
        )

    # uninterrupted run (writes checkpoints as it goes)
    a = run_one(tmp_path / "out-a", tmp_path / "ckpt")
    # simulate death one pass before the end: promote prev -> cur
    cur, prev = _mp_ckpt_paths(str(tmp_path / "ckpt"), 0)
    assert os.path.exists(prev)
    shutil.copy(prev, cur)
    b = run_one(tmp_path / "out-b", tmp_path / "ckpt")
    # resumed final model == uninterrupted final model, bit for bit
    np.testing.assert_array_equal(
        np.asarray(a.get_model("global").model.coefficients.means),
        np.asarray(b.get_model("global").model.coefficients.means),
    )
    ra, rb = a.get_model("per-user"), b.get_model("per-user")
    assert set(ra.entity_ids) == set(rb.entity_ids)
    for eid in ra.entity_ids:
        np.testing.assert_array_equal(
            ra.coefficients_for_entity(eid), rb.coefficients_for_entity(eid),
            err_msg=str(eid),
        )

    # a full-state checkpoint resumes to a no-op retrain with the same model
    c = run_one(tmp_path / "out-c", tmp_path / "ckpt")
    np.testing.assert_array_equal(
        np.asarray(a.get_model("global").model.coefficients.means),
        np.asarray(c.get_model("global").model.coefficients.means),
    )

    # a fingerprint mismatch (different reg sweep) ignores the checkpoint
    args = make_args(tmp_path / "out-d", tmp_path / "ckpt")
    args.coordinate_configurations[0] = (
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=60,"
        "tolerance=1e-9,regularization=L2,reg.weights=0.7"
    )
    shard_configs = dict(
        parse_feature_shard_configuration(a)
        for a in args.feature_shard_configurations
    )
    coord_configs = dict(
        parse_coordinate_configuration(a) for a in args.coordinate_configurations
    )
    os.makedirs(tmp_path / "out-d", exist_ok=True)
    run_multiprocess_game(
        args, 0, 1, PhotonLogger(str(tmp_path / "out-d" / "log.txt")),
        str(tmp_path / "out-d"),
        TaskType("LOGISTIC_REGRESSION"), coord_configs, shard_configs,
        _load_index_maps(args.off_heap_index_map_directory, shard_configs),
    )
    d_model = load_game_model(
        str(tmp_path / "out-d" / "best"), {"global": fe_imap, "per-user": re_imap}
    )
    # trained fresh under the different weight: coefficients differ
    assert not np.array_equal(
        np.asarray(a.get_model("global").model.coefficients.means),
        np.asarray(d_model.get_model("global").model.coefficients.means),
    )


def test_two_process_game_checkpoint_resume(tmp_path):
    """Cross-rank checkpoint resume: ranks can die one generation apart, so
    resume picks the latest cursor EVERY rank can serve (rank 1's previous
    generation here) and the resumed 2-process run reproduces the
    uninterrupted model bit for bit."""
    import shutil

    import numpy as np

    from photon_ml_tpu.cli.distributed_training import _mp_ckpt_paths
    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap
    from photon_ml_tpu.io.model_io import load_game_model

    rng = np.random.default_rng(167)
    d, n_users = 3, 6
    w_true = rng.normal(size=d)
    u_eff = 1.4 * rng.normal(size=n_users)
    fe_imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    re_imap = IndexMap.build(["bias\x01"], add_intercept=False)
    (tmp_path / "index-maps").mkdir()
    fe_imap.save(str(tmp_path / "index-maps" / "global.npz"))
    re_imap.save(str(tmp_path / "index-maps" / "re.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            u = int(r.integers(0, n_users))
            y = float((x @ w_true + u_eff[u] + 0.3 * r.normal()) > 0)
            yield {
                "uid": f"{seed}-{i}",
                "label": y,
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ] + [{"name": "bias", "term": "", "value": 1.0}],
                "metadataMap": {"userId": f"u{u}"},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(130, seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "in" / "part-b.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(110, seed=2),
    )

    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    worker = os.path.join(REPO, "tests", "mp_game_worker.py")

    def run2(tag):
        port = _free_port()
        shutil.rmtree(tmp_path / "out", ignore_errors=True)
        logs = [open(tmp_path / f"{tag}{i}.log", "w+") for i in range(2)]
        procs = [
            subprocess.Popen(
                [sys.executable, worker, str(i), "2", str(port), str(tmp_path),
                 "--coordinate-descent-iterations", "2",
                 "--checkpoint-directory", str(tmp_path / "ckpt")],
                env=env, stdout=logs[i], stderr=subprocess.STDOUT, text=True,
            )
            for i in range(2)
        ]
        try:
            for i, p in enumerate(procs):
                rc = p.wait(timeout=300)
                assert rc == 0, (
                    f"{tag} {i} failed:\n"
                    + (tmp_path / f"{tag}{i}.log").read_text()
                )
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            for f in logs:
                f.close()
        return load_game_model(
            str(tmp_path / "out" / "best"),
            {"global": fe_imap, "per-user": re_imap},
        )

    a = run2("ck")
    fe_a = np.asarray(a.get_model("global").model.coefficients.means)
    re_a = {
        str(e): np.asarray(a.get_model("per-user").coefficients_for_entity(e))
        for e in a.get_model("per-user").entity_ids
    }
    # ranks die one generation apart: rank1 loses its last checkpoint
    cur1, prev1 = _mp_ckpt_paths(str(tmp_path / "ckpt"), 1)
    assert os.path.exists(prev1)
    shutil.copy(prev1, cur1)
    b = run2("ckr")
    assert "resuming from checkpoint" in (tmp_path / "ckr0.log").read_text()
    np.testing.assert_array_equal(
        fe_a, np.asarray(b.get_model("global").model.coefficients.means)
    )
    rb = b.get_model("per-user")
    for eid, va in re_a.items():
        np.testing.assert_array_equal(
            va, np.asarray(rb.coefficients_for_entity(eid)), err_msg=eid
        )


def test_multiprocess_fe_checkpoint_resume(tmp_path):
    """Per-config checkpoint/resume in the fixed-effect-only sweep: deleting
    the last config's file resumes with only that config retrained, and a
    full set of files resumes to a no-op — both bit-identical to the
    uninterrupted run, with variances and evaluations preserved."""
    import numpy as np

    from photon_ml_tpu.cli.distributed_training import run_multiprocess_fixed_effect
    from photon_ml_tpu.cli.game_training_driver import (
        _load_index_maps,
        build_arg_parser,
    )
    from photon_ml_tpu.cli.parsers import (
        parse_coordinate_configuration,
        parse_feature_shard_configuration,
    )
    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap
    from photon_ml_tpu.io.model_io import load_game_model
    from photon_ml_tpu.types import TaskType
    from photon_ml_tpu.util import PhotonLogger

    rng = np.random.default_rng(173)
    d = 4
    w_true = rng.normal(size=d)
    imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    (tmp_path / "index-maps").mkdir()
    imap.save(str(tmp_path / "index-maps" / "global.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            yield {
                "uid": f"{seed}-{i}",
                "label": float((x @ w_true + 0.3 * r.normal()) > 0),
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ],
                "metadataMap": {},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    (tmp_path / "val").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(160, seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "val" / "part-0.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(100, seed=2),
    )

    def run_one(out):
        args = build_arg_parser().parse_args([
            "--input-data-directories", str(tmp_path / "in"),
            "--validation-data-directories", str(tmp_path / "val"),
            "--root-output-directory", str(out),
            "--feature-shard-configurations", "name=global,feature.bags=features",
            "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
            "--training-task", "LOGISTIC_REGRESSION",
            "--coordinate-update-sequence", "global",
            "--coordinate-configurations",
            "name=global,feature.shard=global,optimizer=LBFGS,max.iter=80,"
            "tolerance=1e-9,regularization=L2,reg.weights=0.3|3|30",
            "--variance-computation-type", "SIMPLE",
            "--checkpoint-directory", str(tmp_path / "ckpt"),
        ])
        shard_configs = dict(
            parse_feature_shard_configuration(a)
            for a in args.feature_shard_configurations
        )
        coord_configs = dict(
            parse_coordinate_configuration(a) for a in args.coordinate_configurations
        )
        os.makedirs(out, exist_ok=True)
        run_multiprocess_fixed_effect(
            args, 0, 1, PhotonLogger(str(out / "log.txt")), str(out),
            TaskType("LOGISTIC_REGRESSION"), coord_configs, shard_configs,
            _load_index_maps(args.off_heap_index_map_directory, shard_configs),
        )
        return load_game_model(str(out / "best"), {"global": imap})

    a = run_one(tmp_path / "out-a")
    ca = a.get_model("global").model.coefficients

    # interruption after config 1: remove config 2's file
    cfg_files = sorted((tmp_path / "ckpt").glob("mp-fe-cfg*.npz"))
    assert len(cfg_files) == 3
    cfg_files[-1].unlink()
    b = run_one(tmp_path / "out-b")
    assert "resuming from checkpoint: 2 configs done" in (
        tmp_path / "out-b" / "log.txt"
    ).read_text()
    cb = b.get_model("global").model.coefficients
    np.testing.assert_array_equal(np.asarray(ca.means), np.asarray(cb.means))
    np.testing.assert_array_equal(
        np.asarray(ca.variances), np.asarray(cb.variances)
    )

    # full set: no-op resume
    c = run_one(tmp_path / "out-c")
    assert "resuming from checkpoint: 3 configs done" in (
        tmp_path / "out-c" / "log.txt"
    ).read_text()
    cc = c.get_model("global").model.coefficients
    np.testing.assert_array_equal(np.asarray(ca.means), np.asarray(cc.means))


def test_two_process_game_hyperparameter_tuning(tmp_path):
    """Bayesian hyperparameter tuning in multi-process GAME training: every
    rank's GP proposes identical candidates (deterministic from identical
    gathered observations), tuned configs train through the shared exchange
    machinery, and selection picks across grid + tuned results — matching
    the single-process driver's tuned selection on the same data."""
    import json as _json

    import numpy as np

    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap

    rng = np.random.default_rng(179)
    d, n_users = 3, 6
    w_true = rng.normal(size=d)
    u_eff = 1.4 * rng.normal(size=n_users)
    fe_imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    re_imap = IndexMap.build(["bias\x01"], add_intercept=False)
    (tmp_path / "index-maps").mkdir()
    fe_imap.save(str(tmp_path / "index-maps" / "global.npz"))
    re_imap.save(str(tmp_path / "index-maps" / "re.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            u = int(r.integers(0, n_users))
            y = float((x @ w_true + u_eff[u] + 0.3 * r.normal()) > 0)
            yield {
                "uid": f"{seed}-{i}",
                "label": y,
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ] + [{"name": "bias", "term": "", "value": 1.0}],
                "metadataMap": {"userId": f"u{u}"},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    (tmp_path / "val").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(140, seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "in" / "part-b.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(120, seed=2),
    )
    avro_io.write_container(
        str(tmp_path / "val" / "part-0.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(110, seed=3),
    )

    tuning = [
        "--hyper-parameter-tuning", "BAYESIAN",
        "--hyper-parameter-tuning-iterations", "2",
        "--coordinate-descent-iterations", "1",
        "--output-mode", "TUNED",
    ]
    port = _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    worker = os.path.join(REPO, "tests", "mp_game_worker.py")
    logs = [open(tmp_path / f"tune{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(tmp_path),
             "--validation-data-directories", str(tmp_path / "val"), *tuning],
            env=env, stdout=logs[i], stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=420)
            assert rc == 0, (
                f"tune {i} failed:\n" + (tmp_path / f"tune{i}.log").read_text()
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()

    summary = _json.loads((tmp_path / "out" / "summary.json").read_text())
    rows = summary["results"]
    assert len(rows) == 3  # 1 grid config + 2 tuned candidates
    assert all(r["value"] is not None for r in rows)
    # the tuned candidates explored DIFFERENT reg weights than the grid
    weights = [r["regularization_weight"]["global"] for r in rows]
    assert len(set(round(w, 8) for w in weights)) >= 2
    values = [r["value"] for r in rows]
    assert summary["best_index"] == int(np.argmax(values))
    # TUNED output mode: tuned configs saved under models/<i>/
    for i in (1, 2):
        assert (tmp_path / "out" / "models" / str(i)).is_dir()
    assert (tmp_path / "out" / "best").is_dir()

    # PER-CANDIDATE parity with the single-process driver on the same data
    # and seeds: identical observations feed the GP, so the SAME candidates
    # must be proposed and trained (tuned candidates cold-start in both
    # paths), and the selected model must agree
    _run_single_process_driver(tmp_path, "sp-tune.log", [
        "--input-data-directories", str(tmp_path / "in"),
        "--validation-data-directories", str(tmp_path / "val"),
        "--root-output-directory", str(tmp_path / "out-single"),
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--feature-shard-configurations", "name=re,feature.bags=features",
        "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
        "--training-task", "LOGISTIC_REGRESSION",
        "--coordinate-update-sequence", "global,per-user",
        "--coordinate-configurations",
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=80,"
        "tolerance=1e-9,regularization=L2,reg.weights=1.0",
        "--coordinate-configurations",
        "name=per-user,feature.shard=re,random.effect.type=userId,"
        "optimizer=LBFGS,max.iter=60,tolerance=1e-9,regularization=L2,"
        "reg.weights=1.0",
        *tuning,
    ], timeout=420)
    for i in (1, 2):
        for cid in ("global", "per-user"):
            w_sp = _spec_reg_weight(tmp_path / "out-single" / "models" / str(i), cid)
            w_mp = _spec_reg_weight(tmp_path / "out" / "models" / str(i), cid)
            assert w_mp == pytest.approx(w_sp, rel=1e-6), f"candidate {i} {cid}"
    assert _spec_reg_weight(tmp_path / "out" / "best", "global") == pytest.approx(
        _spec_reg_weight(tmp_path / "out-single" / "best", "global"), rel=1e-6
    )
    from photon_ml_tpu.io.model_io import load_game_model

    fe_imaps = {"global": fe_imap, "per-user": re_imap}
    ref = load_game_model(str(tmp_path / "out-single" / "best"), fe_imaps)
    got = load_game_model(str(tmp_path / "out" / "best"), fe_imaps)
    np.testing.assert_allclose(
        np.asarray(got.get_model("global").model.coefficients.means),
        np.asarray(ref.get_model("global").model.coefficients.means),
        atol=2e-3,
    )


def test_multiprocess_game_tuning_checkpoint_resume(tmp_path):
    """Checkpoint resume THROUGH hyperparameter tuning: a job killed after a
    tuned candidate completes resumes with only the REMAINING iterations
    (restored tuned entries feed the GP as observations) and reproduces the
    uninterrupted run's results exactly."""
    import json as _json
    import shutil

    import numpy as np

    from photon_ml_tpu.cli.distributed_training import run_multiprocess_game
    from photon_ml_tpu.cli.game_training_driver import (
        _load_index_maps,
        build_arg_parser,
    )
    from photon_ml_tpu.cli.parsers import (
        parse_coordinate_configuration,
        parse_feature_shard_configuration,
    )
    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap
    from photon_ml_tpu.types import TaskType
    from photon_ml_tpu.util import PhotonLogger

    rng = np.random.default_rng(191)
    d, n_users = 3, 5
    w_true = rng.normal(size=d)
    u_eff = 1.4 * rng.normal(size=n_users)
    fe_imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    re_imap = IndexMap.build(["bias\x01"], add_intercept=False)
    (tmp_path / "index-maps").mkdir()
    fe_imap.save(str(tmp_path / "index-maps" / "global.npz"))
    re_imap.save(str(tmp_path / "index-maps" / "re.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            u = int(r.integers(0, n_users))
            y = float((x @ w_true + u_eff[u] + 0.3 * r.normal()) > 0)
            yield {
                "uid": f"{seed}-{i}",
                "label": y,
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ] + [{"name": "bias", "term": "", "value": 1.0}],
                "metadataMap": {"userId": f"u{u}"},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    (tmp_path / "val").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(170, seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "val" / "part-0.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(100, seed=2),
    )

    def run_one(out):
        args = build_arg_parser().parse_args([
            "--input-data-directories", str(tmp_path / "in"),
            "--validation-data-directories", str(tmp_path / "val"),
            "--root-output-directory", str(out),
            "--feature-shard-configurations", "name=global,feature.bags=features",
            "--feature-shard-configurations", "name=re,feature.bags=features",
            "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
            "--training-task", "LOGISTIC_REGRESSION",
            "--coordinate-update-sequence", "global,per-user",
            "--coordinate-configurations",
            "name=global,feature.shard=global,optimizer=LBFGS,max.iter=60,"
            "tolerance=1e-9,regularization=L2,reg.weights=1.0",
            "--coordinate-configurations",
            "name=per-user,feature.shard=re,random.effect.type=userId,"
            "optimizer=LBFGS,max.iter=40,tolerance=1e-9,regularization=L2,"
            "reg.weights=1.0",
            "--coordinate-descent-iterations", "1",
            "--hyper-parameter-tuning", "BAYESIAN",
            "--hyper-parameter-tuning-iterations", "2",
            "--checkpoint-directory", str(tmp_path / "ckpt"),
        ])
        shard_configs = dict(
            parse_feature_shard_configuration(a)
            for a in args.feature_shard_configurations
        )
        coord_configs = dict(
            parse_coordinate_configuration(a) for a in args.coordinate_configurations
        )
        os.makedirs(out, exist_ok=True)
        return run_multiprocess_game(
            args, 0, 1, PhotonLogger(str(out / "log.txt")), str(out),
            TaskType("LOGISTIC_REGRESSION"), coord_configs, shard_configs,
            _load_index_maps(args.off_heap_index_map_directory, shard_configs),
        )

    a = run_one(tmp_path / "out-a")
    rows_a = a["results"]
    assert len(rows_a) == 3  # 1 grid + 2 tuned

    # simulate death after tuned candidate 1 (config index 1) completed:
    # delete config 2's snapshot and roll the live state back one generation
    (tmp_path / "ckpt" / "mp-game-cfg0002-r00000.npz").unlink()
    from photon_ml_tpu.cli.distributed_training import _mp_ckpt_paths

    cur, prev = _mp_ckpt_paths(str(tmp_path / "ckpt"), 0)
    b = run_one(tmp_path / "out-b")
    rows_b = b["results"]
    assert len(rows_b) == 3  # NOT 4: only the remaining iteration ran
    # ALL rows must match — including the RE-PROPOSED candidate 2: the tuner
    # fast-forwards its Sobol stream past the restored candidate's draws, so
    # the resumed run proposes the uninterrupted run's candidate 2, not a
    # duplicate of candidate 1 (the stream position depends only on draws,
    # never on observations)
    for ra, rb in zip(rows_a, rows_b):
        assert ra["regularization_weight"] == rb["regularization_weight"]
        assert ra["value"] == rb["value"]
    weights = [r["regularization_weight"]["global"] for r in rows_b]
    assert weights[2] != weights[1]  # candidate 2 is not a re-trained candidate 1
    assert b["best_index"] == a["best_index"]


# --------------------------------------------------------------------------
# round-5 additions: down-sampling, box constraints, FE-only tuning — each a
# two-process run compared against the SINGLE-PROCESS driver run in a
# subprocess (same f32 numeric mode as the workers; the in-process suite
# runs x64, which would blur what is exchange drift vs dtype drift)


def _mp_env():
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    return env


def _run_single_process_driver(tmp_path, log_name, argv, timeout=300):
    log_path = tmp_path / log_name
    with open(log_path, "w+") as log:
        p = subprocess.Popen(
            [sys.executable, "-m", "photon_ml_tpu.cli.game_training_driver", *argv],
            env=_mp_env(), stdout=log, stderr=subprocess.STDOUT, text=True,
        )
        rc = p.wait(timeout=timeout)
    assert rc == 0, f"single-process driver failed:\n{log_path.read_text()}"


def _run_workers(tmp_path, worker, log_prefix, extra, n=2, timeout=300):
    port = _free_port()
    logs = [open(tmp_path / f"{log_prefix}{i}.log", "w+") for i in range(n)]
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", worker),
             str(i), str(n), str(port), str(tmp_path), *extra],
            env=_mp_env(), stdout=logs[i], stderr=subprocess.STDOUT, text=True,
        )
        for i in range(n)
    ]
    try:
        for i, p in enumerate(procs):
            rc = p.wait(timeout=timeout)
            assert rc == 0, (
                f"{log_prefix}{i} failed:\n"
                + (tmp_path / f"{log_prefix}{i}.log").read_text()
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for lg in logs:
            lg.close()


def _spec_reg_weight(model_dir, cid):
    """The reg weight a saved model was trained with, from model-spec.json."""
    import json as _json

    from photon_ml_tpu.cli.parsers import parse_coordinate_configuration

    spec = _json.loads((model_dir / "model-spec.json").read_text())
    _, cfg = parse_coordinate_configuration(spec[cid])
    return (
        cfg.reg_weights[0]
        if cfg.reg_weights
        else cfg.optimization_config.regularization_weight
    )


def _fe_classification_inputs(tmp_path, rng_seed=3, d=4, n=400):
    """Two uneven training part files + one validation file for a logistic
    fixed-effect run; returns the index map."""
    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap

    rng = np.random.default_rng(rng_seed)
    w_true = rng.normal(size=d)
    imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    (tmp_path / "index-maps").mkdir()
    imap.save(str(tmp_path / "index-maps" / "global.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            y = float((x @ w_true + 0.3 * r.normal()) > 0)
            yield {
                "uid": f"{seed}-{i}",
                "label": y,
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ],
                "metadataMap": {},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    (tmp_path / "val").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(n // 2 + 37, seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "in" / "part-b.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(n // 2 - 37, seed=2),
    )
    avro_io.write_container(
        str(tmp_path / "val" / "part-0.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(150, seed=5),
    )
    return imap


def _fe_common_argv(tmp_path, out_dir, coord_config):
    return [
        "--input-data-directories", str(tmp_path / "in"),
        "--validation-data-directories", str(tmp_path / "val"),
        "--root-output-directory", str(out_dir),
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
        "--training-task", "LOGISTIC_REGRESSION",
        "--coordinate-update-sequence", "global",
        "--coordinate-configurations", coord_config,
        "--evaluators", "AUC",
    ]


def _best_fe_coeffs(root, imap):
    from photon_ml_tpu.io.model_io import load_game_model

    gm = load_game_model(str(root / "best"), {"global": imap})
    return np.asarray(gm.get_model("global").model.coefficients.means)


def test_two_process_fe_down_sampling_parity(tmp_path):
    """Multi-process fixed-effect DOWN-SAMPLING (restriction lifted): the
    keep-draws are keyed by each sample's position in the single-process
    concatenated row order (per_sample_uniform), so a 2-process run draws
    the SAME masks as the single-process driver — per-pass redraws, warm
    starts and per-update validation selection included. Parity bar: the
    saved best model matches the single-process subprocess run."""
    imap = _fe_classification_inputs(tmp_path)
    cc = (
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=100,"
        "tolerance=1e-9,regularization=L2,reg.weights=0.1|10,"
        "down.sampling.rate=0.6"
    )
    extra = [
        "--coordinate-configurations", cc,
        "--coordinate-descent-iterations", "2",
    ]
    _run_single_process_driver(
        tmp_path, "sp-ds.log",
        _fe_common_argv(tmp_path, tmp_path / "out-single", cc)
        + ["--coordinate-descent-iterations", "2"],
    )
    _run_workers(tmp_path, "mp_train_worker.py", "ds", extra)

    expected = _best_fe_coeffs(tmp_path / "out-single", imap)
    got = _best_fe_coeffs(tmp_path / "out", imap)
    # identical masks; the residual drift is f32 psum-order arithmetic on
    # O(10) coefficients (a WRONG mask diverges by orders of magnitude)
    np.testing.assert_allclose(got, expected, rtol=5e-4, atol=5e-4)
    # same selected reg weight
    assert _spec_reg_weight(tmp_path / "out" / "best", "global") == pytest.approx(
        _spec_reg_weight(tmp_path / "out-single" / "best", "global")
    )
    # the masks actually did something: a no-down-sampling run differs
    _run_workers(
        tmp_path, "mp_train_worker.py", "nods",
        ["--coordinate-configurations", cc.replace(",down.sampling.rate=0.6", ""),
         "--root-output-directory", str(tmp_path / "out-nods")],
    )
    assert not np.allclose(
        _best_fe_coeffs(tmp_path / "out-nods", imap), got, atol=1e-6
    )


def test_two_process_fe_box_constraints_parity(tmp_path):
    """Multi-process BOX CONSTRAINTS (restriction lifted): the driver-level
    constraint map compiles to per-feature bound vectors exactly as the
    single-process driver (GLMSuite.createConstraintFeatureMap semantics) and
    rides the sharded solver's native bound support. The trained model must
    match the single-process run and respect the bounds."""
    import json as _json

    imap = _fe_classification_inputs(tmp_path, rng_seed=11)
    constraints = _json.dumps([
        {"name": "f0", "term": "", "lowerBound": -0.01, "upperBound": 0.01},
        {"name": "f1", "term": "", "lowerBound": 0.0, "upperBound": 0.05},
    ])
    # LBFGSB: the projected-gradient active-set solver converges to the
    # unique constrained optimum on both paths (post-step-projection LBFGS
    # is path-dependent near active bounds)
    cc = (
        "name=global,feature.shard=global,optimizer=LBFGSB,max.iter=100,"
        "tolerance=1e-9,regularization=L2,reg.weights=0.1|10"
    )
    _run_single_process_driver(
        tmp_path, "sp-box.log",
        _fe_common_argv(tmp_path, tmp_path / "out-single", cc)
        + ["--coefficient-box-constraints", constraints],
    )
    _run_workers(
        tmp_path, "mp_train_worker.py", "box",
        ["--coordinate-configurations", cc,
         "--coefficient-box-constraints", constraints],
    )

    expected = _best_fe_coeffs(tmp_path / "out-single", imap)
    got = _best_fe_coeffs(tmp_path / "out", imap)
    np.testing.assert_allclose(got, expected, atol=1e-4)
    from photon_ml_tpu.data.index_map import feature_key

    i0 = imap.get_index(feature_key("f0", ""))
    i1 = imap.get_index(feature_key("f1", ""))
    assert -0.01 <= got[i0] <= 0.01
    assert 0.0 <= got[i1] <= 0.05
    # the constraint is ACTIVE (otherwise this proves nothing); the control
    # run drops the bounds, so it solves with plain LBFGS
    _run_workers(
        tmp_path, "mp_train_worker.py", "nobox",
        ["--coordinate-configurations", cc.replace("LBFGSB", "LBFGS"),
         "--root-output-directory", str(tmp_path / "out-nobox")],
    )
    free = _best_fe_coeffs(tmp_path / "out-nobox", imap)
    assert abs(free[i0]) > 0.01 or not (0.0 <= free[i1] <= 0.05)


def test_two_process_fe_hyperparameter_tuning_parity(tmp_path):
    """FE-only multi-process HYPERPARAMETER TUNING (restriction lifted),
    routed through the lockstep-GP design: every rank proposes identical
    candidates from identical gathered observations. Per-candidate parity
    with the single-process driver: the SAME candidate reg weights are
    proposed and trained, and the selected model matches."""
    imap = _fe_classification_inputs(tmp_path, rng_seed=29)
    cc = (
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=100,"
        "tolerance=1e-9,regularization=L2,reg.weights=1.0"
    )
    tuning = [
        "--hyper-parameter-tuning", "BAYESIAN",
        "--hyper-parameter-tuning-iterations", "2",
        "--output-mode", "ALL",
    ]
    _run_single_process_driver(
        tmp_path, "sp-tune.log",
        _fe_common_argv(tmp_path, tmp_path / "out-single", cc) + tuning,
    )
    _run_workers(
        tmp_path, "mp_train_worker.py", "fetune",
        ["--coordinate-configurations", cc, *tuning],
    )

    import json as _json

    summary = _json.loads((tmp_path / "out" / "summary.json").read_text())
    rows = summary["results"]
    assert len(rows) == 3  # 1 grid config + 2 tuned candidates
    assert all(r["value"] is not None for r in rows)
    # PER-CANDIDATE parity: the tuned reg weights agree with the
    # single-process run's (identical observations -> identical proposals)
    for i in range(3):
        w_sp = _spec_reg_weight(tmp_path / "out-single" / "models" / str(i), "global")
        w_mp = _spec_reg_weight(tmp_path / "out" / "models" / str(i), "global")
        assert w_mp == pytest.approx(w_sp, rel=1e-6), f"candidate {i}"
    # tuned candidates actually explored beyond the grid
    weights = [r["regularization_weight"] for r in rows]
    assert len({round(w, 8) for w in weights}) >= 2
    # selection parity
    np.testing.assert_allclose(
        _best_fe_coeffs(tmp_path / "out", imap),
        _best_fe_coeffs(tmp_path / "out-single", imap),
        atol=1e-4,
    )



def _game_classification_inputs(tmp_path, rng_seed, n_users, rows, val_rows=None,
                                d=4):
    """GAME (fixed + per-user) training inputs: index maps + uneven part
    files (+ optional validation file); the shared fixture behind the
    down-sampling GAME parity tests. Returns (fe_imap, re_imap)."""
    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.index_map import IndexMap

    rng = np.random.default_rng(rng_seed)
    w_true = rng.normal(size=d)
    u_eff = 1.2 * rng.normal(size=n_users)
    fe_imap = IndexMap.build([f"f{j}\x01" for j in range(d)], add_intercept=True)
    re_imap = IndexMap.build(["bias\x01"], add_intercept=False)
    (tmp_path / "index-maps").mkdir()
    fe_imap.save(str(tmp_path / "index-maps" / "global.npz"))
    re_imap.save(str(tmp_path / "index-maps" / "re.npz"))

    def records(n_rows, seed):
        r = np.random.default_rng(seed)
        for i in range(n_rows):
            x = r.normal(size=d)
            u = int(r.integers(0, n_users))
            y = float((x @ w_true + u_eff[u] + 0.3 * r.normal()) > 0)
            yield {
                "uid": f"{seed}-{i}",
                "label": y,
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[j])}
                    for j in range(d)
                ] + [{"name": "bias", "term": "", "value": 1.0}],
                "metadataMap": {"userId": f"u{u}"},
                "weight": 1.0,
                "offset": 0.0,
            }

    (tmp_path / "in").mkdir()
    avro_io.write_container(
        str(tmp_path / "in" / "part-a.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(rows[0], seed=1),
    )
    avro_io.write_container(
        str(tmp_path / "in" / "part-b.avro"),
        avro_io.TRAINING_EXAMPLE_SCHEMA, records(rows[1], seed=2),
    )
    if val_rows:
        (tmp_path / "val").mkdir()
        avro_io.write_container(
            str(tmp_path / "val" / "part-0.avro"),
            avro_io.TRAINING_EXAMPLE_SCHEMA, records(val_rows, seed=5),
        )
    return fe_imap, re_imap


def _assert_best_game_models_match(tmp_path, fe_imap, re_imap, atol=2e-3):
    """best/ parity between out-single/ and out/: fixed-effect coefficients
    and every per-entity random-effect row."""
    from photon_ml_tpu.io.model_io import load_game_model

    imaps = {"global": fe_imap, "per-user": re_imap}
    ref = load_game_model(str(tmp_path / "out-single" / "best"), imaps)
    got = load_game_model(str(tmp_path / "out" / "best"), imaps)
    np.testing.assert_allclose(
        np.asarray(got.get_model("global").model.coefficients.means),
        np.asarray(ref.get_model("global").model.coefficients.means),
        atol=atol,
    )
    re_ref, re_got = ref.get_model("per-user"), got.get_model("per-user")
    assert set(re_got.entity_ids) == set(re_ref.entity_ids)
    for eid in re_ref.entity_ids:
        np.testing.assert_allclose(
            re_got.coefficients_for_entity(eid),
            re_ref.coefficients_for_entity(eid),
            atol=atol, err_msg=str(eid),
        )


def test_two_process_game_fe_down_sampling_parity(tmp_path):
    """GAME multi-process training with fixed-effect down-sampling: the FE
    coordinate redraws its mask per CD pass (call index = pass, sampler
    rebuilt per config — the single-process estimator's counter), random
    effects train on the full data, and the saved model matches the
    single-process driver."""
    fe_imap, re_imap = _game_classification_inputs(
        tmp_path, rng_seed=41, n_users=9, rows=(190, 150)
    )

    ds_cc = (
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=80,"
        "tolerance=1e-9,regularization=L2,reg.weights=1.0,"
        "down.sampling.rate=0.7"
    )
    _run_single_process_driver(tmp_path, "sp-gds.log", [
        "--input-data-directories", str(tmp_path / "in"),
        "--root-output-directory", str(tmp_path / "out-single"),
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--feature-shard-configurations", "name=re,feature.bags=features",
        "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
        "--training-task", "LOGISTIC_REGRESSION",
        "--coordinate-update-sequence", "global,per-user",
        "--coordinate-configurations", ds_cc,
        "--coordinate-configurations",
        "name=per-user,feature.shard=re,random.effect.type=userId,"
        "optimizer=LBFGS,max.iter=60,tolerance=1e-9,regularization=L2,"
        "reg.weights=1.0",
        "--coordinate-descent-iterations", "2",
    ])
    # the extra --coordinate-configurations OVERRIDES the worker's built-in
    # "global" coordinate (dict() keeps the LAST entry per name)
    _run_workers(
        tmp_path, "mp_game_worker.py", "gds",
        ["--coordinate-configurations", ds_cc],
    )

    _assert_best_game_models_match(tmp_path, fe_imap, re_imap)


def test_multiprocess_fe_tuning_checkpoint_resume(tmp_path):
    """FE-only checkpoint resume THROUGH hyperparameter tuning: a job killed
    after a tuned candidate completes resumes with only the remaining
    iterations, reconstructs the restored tuned candidate's config from the
    checkpoint's weight metadata (it is NOT derivable from the grid), and —
    because the tuner fast-forwards its Sobol stream — reproduces the
    uninterrupted run's candidates exactly."""
    from photon_ml_tpu.cli.distributed_training import run_multiprocess_fixed_effect
    from photon_ml_tpu.cli.game_training_driver import (
        _load_index_maps,
        build_arg_parser,
    )
    from photon_ml_tpu.cli.parsers import (
        parse_coordinate_configuration,
        parse_feature_shard_configuration,
    )
    from photon_ml_tpu.types import TaskType
    from photon_ml_tpu.util import PhotonLogger

    _fe_classification_inputs(tmp_path, rng_seed=53)

    def run_one(out):
        args = build_arg_parser().parse_args([
            *_fe_common_argv(
                tmp_path, out,
                "name=global,feature.shard=global,optimizer=LBFGS,max.iter=80,"
                "tolerance=1e-9,regularization=L2,reg.weights=1.0",
            ),
            "--coordinate-descent-iterations", "1",
            "--hyper-parameter-tuning", "BAYESIAN",
            "--hyper-parameter-tuning-iterations", "2",
            "--checkpoint-directory", str(tmp_path / "ckpt"),
        ])
        shard_configs = dict(
            parse_feature_shard_configuration(a)
            for a in args.feature_shard_configurations
        )
        coord_configs = dict(
            parse_coordinate_configuration(a) for a in args.coordinate_configurations
        )
        os.makedirs(out, exist_ok=True)
        return run_multiprocess_fixed_effect(
            args, 0, 1, PhotonLogger(str(out / "log.txt")), str(out),
            TaskType("LOGISTIC_REGRESSION"), coord_configs, shard_configs,
            _load_index_maps(args.off_heap_index_map_directory, shard_configs),
        )

    a = run_one(tmp_path / "out-a")
    rows_a = a["results"]
    assert len(rows_a) == 3  # 1 grid + 2 tuned

    # simulate death after tuned candidate 1 (config 1) completed: delete
    # config 2's per-config checkpoint file
    (tmp_path / "ckpt" / "mp-fe-cfg0002-r00000.npz").unlink()
    b = run_one(tmp_path / "out-b")
    rows_b = b["results"]
    assert len(rows_b) == 3  # only the remaining iteration ran
    for ra, rb in zip(rows_a, rows_b):
        assert ra["regularization_weight"] == rb["regularization_weight"]
        assert ra["value"] == rb["value"]
    weights = [r["regularization_weight"] for r in rows_b]
    assert weights[2] != weights[1]  # not a re-trained duplicate of candidate 1
    assert b["best_index"] == a["best_index"]


def test_multiprocess_data_summary_matches_single_process(tmp_path):
    """--data-summary-directory in the multi-process FE runner (restriction
    lifted): the per-shard FeatureSummarizationResultAvro is computed from
    the GLOBAL statistics (per-rank column sums meeting in an allgather) and
    must match the single-process driver's file feature by feature."""
    from photon_ml_tpu.data import avro_io

    _fe_classification_inputs(tmp_path, rng_seed=71)
    cc = (
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=60,"
        "tolerance=1e-9,regularization=L2,reg.weights=1.0"
    )
    _run_single_process_driver(
        tmp_path, "sp-summary.log",
        _fe_common_argv(tmp_path, tmp_path / "out-single", cc)
        + ["--data-summary-directory", str(tmp_path / "summary-single")],
    )
    _run_workers(
        tmp_path, "mp_train_worker.py", "summ",
        ["--coordinate-configurations", cc,
         "--data-summary-directory", str(tmp_path / "summary-mp")],
    )

    def read_summary(d):
        recs = {}
        for rec in avro_io.read_container(
            str(d / "global-feature-summary.avro")
        ):
            recs[(rec["featureName"], rec["featureTerm"])] = rec["metrics"]
        return recs

    sp = read_summary(tmp_path / "summary-single")
    mp = read_summary(tmp_path / "summary-mp")
    assert set(mp) == set(sp) and len(sp) == 5  # 4 features + intercept
    for key, m_sp in sp.items():
        m_mp = mp[key]
        assert set(m_mp) == set(m_sp)
        for metric, v in m_sp.items():
            # bounded by f32-input summation order (the two paths reduce in
            # different orders), not by stats correctness
            assert m_mp[metric] == pytest.approx(v, rel=1e-5, abs=1e-9), (
                key, metric
            )


def test_two_process_game_ds_validation_selection(tmp_path):
    """Down-sampling + per-update validation selection in multi-process GAME
    training: each CD pass's fixed-effect update trains on a RESAMPLED
    objective (fresh mask per pass), every update is a selection candidate,
    and the saved best snapshot must match the single-process driver's —
    the masks AND the per-update tracking must agree for this to hold."""
    fe_imap, re_imap = _game_classification_inputs(
        tmp_path, rng_seed=83, n_users=8, rows=(170, 130), val_rows=120
    )

    ds_cc = (
        "name=global,feature.shard=global,optimizer=LBFGS,max.iter=80,"
        "tolerance=1e-9,regularization=L2,reg.weights=1.0,"
        "down.sampling.rate=0.6"
    )
    argv_tail = [
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--feature-shard-configurations", "name=re,feature.bags=features",
        "--off-heap-index-map-directory", str(tmp_path / "index-maps"),
        "--training-task", "LOGISTIC_REGRESSION",
        "--coordinate-update-sequence", "global,per-user",
        "--coordinate-configurations", ds_cc,
        "--coordinate-configurations",
        "name=per-user,feature.shard=re,random.effect.type=userId,"
        "optimizer=LBFGS,max.iter=60,tolerance=1e-9,regularization=L2,"
        "reg.weights=1.0",
        "--coordinate-descent-iterations", "2",
        "--evaluators", "AUC",
    ]
    _run_single_process_driver(tmp_path, "sp-gdsv.log", [
        "--input-data-directories", str(tmp_path / "in"),
        "--validation-data-directories", str(tmp_path / "val"),
        "--root-output-directory", str(tmp_path / "out-single"),
        *argv_tail,
    ])
    _run_workers(
        tmp_path, "mp_game_worker.py", "gdsv",
        ["--validation-data-directories", str(tmp_path / "val"),
         "--coordinate-configurations", ds_cc, "--evaluators", "AUC"],
    )

    _assert_best_game_models_match(tmp_path, fe_imap, re_imap)
    # the selected best metric agrees too (same update won on both paths)
    import json as _json

    meta_sp = _json.loads(
        (tmp_path / "out-single" / "best" / "model-metadata.json").read_text()
    )
    meta_mp = _json.loads(
        (tmp_path / "out" / "best" / "model-metadata.json").read_text()
    )
    assert meta_mp["bestMetric"] == pytest.approx(meta_sp["bestMetric"], abs=2e-4)
