"""Parallel streaming ingest pipeline tests (data/pipeline.py + the
readers.py parallel paths): bitwise parity against the sequential path across
worker counts and decode engines, manifest-order scheduling, bounded in-flight
memory, DecodedBlock thread-safety/lifetime, worker error propagation, and the
background-overlap primitives."""

import io
import threading
import time

import numpy as np
import pytest

from photon_ml_tpu.data import avro_io, native_avro, pipeline
from photon_ml_tpu.data.readers import read_merged_avro
from photon_ml_tpu.estimators.config import FeatureShardConfiguration

SHARDS = {"shardA": FeatureShardConfiguration(feature_bags=("features",))}


def write_fixture(path, rng, n=300, d=6, with_nulls=True, block_count=4096):
    def records():
        for i in range(n):
            yield {
                "uid": None if (with_nulls and i % 7 == 0) else f"s{i}",
                "label": float(i % 2),
                "features": [
                    {"name": f"f{j}", "term": f"t{j % 2}", "value": float(rng.normal())}
                    for j in range(int(rng.integers(0, d)))
                ],
                "metadataMap": {"userId": f"u{i % 5}", "itemId": f"i{i % 3}", "x": "y"},
                "weight": None if (with_nulls and i % 5 == 0) else 2.0,
                "offset": None if (with_nulls and i % 3 == 0) else 0.25,
            }

    avro_io.write_container(
        path, avro_io.TRAINING_EXAMPLE_SCHEMA, records(), block_count=block_count
    )


def assert_bitwise_equal(a, b):
    """Results (GameInput, index_maps, uids) must agree array for array,
    dtype for dtype — the determinism contract across worker counts."""
    ga, ma, ua = a
    gb, mb, ub = b
    assert ga.has_labels == gb.has_labels
    if ga.has_labels:
        la, lb = np.asarray(ga.labels), np.asarray(gb.labels)
        assert la.dtype == lb.dtype and np.array_equal(la, lb)
    assert np.array_equal(ga.offsets, gb.offsets)
    assert np.array_equal(ga.weights, gb.weights)
    assert set(ga.features) == set(gb.features)
    for s in ga.features:
        xa, xb = ga.features[s].tocsr(), gb.features[s].tocsr()
        assert xa.shape == xb.shape
        assert np.array_equal(xa.indptr, xb.indptr)
        assert np.array_equal(xa.indices, xb.indices)
        assert np.array_equal(xa.data, xb.data)
        assert xa.data.dtype == xb.data.dtype
    assert set(ga.id_columns) == set(gb.id_columns)
    for t in ga.id_columns:
        assert list(ga.id_columns[t]) == list(gb.id_columns[t])
    assert list(ua) == list(ub)
    assert set(ma) == set(mb)
    for s in ma:
        assert ma[s].keys() == mb[s].keys()


class TestParallelParity:
    """Bitwise parity matrix: worker counts x decode engines x layouts."""

    @pytest.mark.parametrize("use_native", [True, False])
    def test_worker_counts_bitwise(self, tmp_path, rng, use_native):
        if use_native and not native_avro.available():
            pytest.skip("native decoder unavailable (no g++)")
        for i in range(3):  # multi-file: row bases span file boundaries
            write_fixture(str(tmp_path / f"part-{i}.avro"), rng, n=200)
        reads = {
            w: read_merged_avro(
                str(tmp_path), SHARDS, id_tags=["userId", "itemId"],
                use_native=use_native, ingest_workers=w,
            )
            for w in (1, 2, 5)
        }
        assert_bitwise_equal(reads[1], reads[2])
        assert_bitwise_equal(reads[1], reads[5])

    def test_multiblock_files(self, tmp_path, rng):
        """Many small blocks per file: row bases, file-anchored uids and the
        in-flight window all get exercised across block boundaries."""
        for i in range(2):
            write_fixture(str(tmp_path / f"p{i}.avro"), rng, n=500, block_count=64)
        seq = read_merged_avro(str(tmp_path), SHARDS, id_tags=["userId"], ingest_workers=1)
        par = read_merged_avro(
            str(tmp_path), SHARDS, id_tags=["userId"], ingest_workers=4, ingest_window=3
        )
        assert_bitwise_equal(seq, par)

    def test_existing_index_maps_respected(self, tmp_path, rng):
        write_fixture(str(tmp_path / "d.avro"), rng)
        _, maps, _ = read_merged_avro(str(tmp_path), SHARDS, ingest_workers=1)
        seq = read_merged_avro(str(tmp_path), SHARDS, index_maps=maps, ingest_workers=1)
        par = read_merged_avro(str(tmp_path), SHARDS, index_maps=maps, ingest_workers=3)
        assert_bitwise_equal(seq, par)

    def test_repeated_parallel_runs_identical(self, tmp_path, rng):
        write_fixture(str(tmp_path / "d.avro"), rng, n=400, block_count=128)
        a = read_merged_avro(str(tmp_path), SHARDS, id_tags=["userId"], ingest_workers=4)
        b = read_merged_avro(str(tmp_path), SHARDS, id_tags=["userId"], ingest_workers=4)
        assert_bitwise_equal(a, b)

    def test_unsupported_schema_falls_back_parallel(self, tmp_path):
        """A schema outside the native set must take the pure-Python path on
        the parallel engine too (sequential-path fallback contract)."""
        schema = {
            "name": "Weird",
            "type": "record",
            "fields": [
                {"name": "label", "type": "double"},
                {"name": "features", "type": {"type": "array",
                                              "items": avro_io.FEATURE_SCHEMA}},
                {"name": "count", "type": "long"},
            ],
        }
        path = str(tmp_path / "w.avro")
        avro_io.write_container(path, schema, [
            {"label": 1.0, "features": [{"name": "a", "term": "", "value": 2.0}],
             "count": 3},
        ])
        seq = read_merged_avro(path, SHARDS, ingest_workers=1)
        par = read_merged_avro(path, SHARDS, ingest_workers=4)
        assert_bitwise_equal(seq, par)
        assert par[0].n == 1


class TestErrorPropagation:
    """A corrupt block surfaces the SAME exception from the parallel paths
    as from the sequential walk."""

    def _read_both(self, path, **kw):
        errs = []
        for w in (1, 4):
            with pytest.raises(Exception) as ei:
                read_merged_avro(path, SHARDS, ingest_workers=w, **kw)
            errs.append(ei.value)
        return errs

    def test_truncated_file(self, tmp_path, rng):
        path = str(tmp_path / "t.avro")
        write_fixture(path, rng, n=200, block_count=64)
        data = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(data[: len(data) - 40])  # cut into the last block
        seq_err, par_err = self._read_both(path)
        assert type(seq_err) is type(par_err)
        assert str(seq_err) == str(par_err)

    def test_negative_record_count(self, tmp_path):
        """Satellite regression: a negative block record count raises
        ValueError from framing AND from container_row_count instead of
        silently skewing totals."""
        path = str(tmp_path / "neg.avro")
        with open(path, "wb") as f:
            avro_io._write_container_header(
                f, avro_io.TRAINING_EXAMPLE_SCHEMA, "null"
            )
            head = io.BytesIO()
            avro_io.write_long(head, -3)  # negative n_records
            avro_io.write_long(head, 0)
            f.write(head.getvalue())
            f.write(avro_io.DEFAULT_SYNC)
        with pytest.raises(ValueError, match="negative record count"):
            list(avro_io.iter_raw_blocks(path))
        with pytest.raises(ValueError, match="negative record count"):
            avro_io.container_row_count(path)

    def test_corrupt_payload_same_exception(self, tmp_path, rng):
        """Garbage record bytes: the native engines reject the block and fall
        back to pure Python, which raises the sequential path's exception."""
        path = str(tmp_path / "c.avro")
        write_fixture(path, rng, n=50)
        data = bytearray(open(path, "rb").read())
        data[-30:-20] = b"\xff" * 10  # stomp inside the (only) block payload
        with open(path, "wb") as f:
            f.write(bytes(data))
        seq_err, par_err = self._read_both(path)
        assert type(seq_err) is type(par_err)


class TestMapOrdered:
    def test_order_preserved_under_jitter(self):
        rng = np.random.default_rng(0)
        delays = rng.uniform(0, 0.01, size=40).tolist()

        def fn(i):
            time.sleep(delays[i])
            return i * i

        out = list(pipeline.map_ordered(range(40), fn, workers=6, window=4))
        assert out == [i * i for i in range(40)]

    def test_exception_propagates_with_type(self):
        def fn(i):
            if i == 7:
                raise KeyError("boom-7")
            return i

        with pytest.raises(KeyError, match="boom-7"):
            list(pipeline.map_ordered(range(20), fn, workers=3))

    def test_workers_one_runs_inline(self):
        main = threading.current_thread()
        seen = []

        def fn(i):
            seen.append(threading.current_thread())
            return i

        assert list(pipeline.map_ordered(range(5), fn, workers=1)) == list(range(5))
        assert all(t is main for t in seen)

    def test_bounded_window_with_slow_consumer(self):
        """The producer must never run more than window+1 items ahead of the
        consumer — the peak-memory contract (O(window) raw payloads)."""
        window = 3
        produced = []

        def items():
            for i in range(30):
                produced.append(i)
                yield i

        consumed = 0
        max_ahead = 0
        for r in pipeline.map_ordered(items(), lambda x: x, workers=4, window=window):
            consumed += 1
            time.sleep(0.002)  # slow consumer
            max_ahead = max(max_ahead, len(produced) - consumed)
        assert consumed == 30
        assert max_ahead <= window + 1, max_ahead

    def test_resolvers(self):
        assert pipeline.resolve_ingest_workers(1) == 1
        assert pipeline.resolve_ingest_workers(6) == 6
        auto = pipeline.resolve_ingest_workers(None)
        assert 1 <= auto <= pipeline.DEFAULT_MAX_WORKERS
        with pytest.raises(ValueError):
            pipeline.resolve_ingest_workers(-2)
        assert pipeline.resolve_window(None, 4) == 8
        with pytest.raises(ValueError):
            pipeline.resolve_window(0, 4)


@pytest.mark.skipif(not native_avro.available(), reason="native decoder unavailable")
class TestDecodedBlockLifetime:
    def _block_payload(self, n=50):
        buf = io.BytesIO()
        schema = avro_io.Schema(avro_io.TRAINING_EXAMPLE_SCHEMA)
        for i in range(n):
            avro_io.encode(buf, schema.root, {
                "uid": f"u{i}", "label": float(i),
                "features": [
                    {"name": f"n{i % 4}", "term": "" if i % 3 else "t", "value": float(i)},
                    {"name": "shared", "term": "t0", "value": 1.0},
                ],
                "metadataMap": {"userId": f"e{i % 5}"},
                "weight": 1.0, "offset": 0.0,
            })
        ftypes = native_avro.field_types_for_schema(
            avro_io.TRAINING_EXAMPLE_SCHEMA["fields"]
        )
        return buf.getvalue(), ftypes

    def test_concurrent_decode_matches_sequential(self):
        """Different blocks decoded and read concurrently (the pipeline's
        thread model) must reproduce the single-thread extraction exactly."""
        from concurrent.futures import ThreadPoolExecutor

        payload, ftypes = self._block_payload()

        def extract():
            with native_avro.decode_block(payload, 50, ftypes) as block:
                labels = block.doubles(1).tolist()
                vocab, ids = block.dedup_keys(2, native_avro.DEDUP_FEATURE_KEYS)
                return labels, [vocab[i] for i in ids]

        reference = extract()
        with ThreadPoolExecutor(max_workers=8) as pool:
            results = list(pool.map(lambda _: extract(), range(32)))
        assert all(r == reference for r in results)

    def test_use_after_close_raises(self):
        payload, ftypes = self._block_payload(n=3)
        block = native_avro.decode_block(payload, 3, ftypes)
        assert block.count(1) == 3
        block.close()
        block.close()  # idempotent
        with pytest.raises(RuntimeError, match="closed"):
            block.count(1)
        with pytest.raises(RuntimeError, match="closed"):
            block.doubles(1)
        with pytest.raises(RuntimeError, match="closed"):
            block.dedup_keys(2, native_avro.DEDUP_FEATURE_KEYS)

    def test_dedup_keys_matches_python_composition(self):
        """Native vocab interning must reproduce feature_key()'s name+term
        composition and the map key/value strings exactly, per entry."""
        from photon_ml_tpu.data.index_map import feature_key

        payload, ftypes = self._block_payload()
        with native_avro.decode_block(payload, 50, ftypes) as block:
            _rows, no, nl, to, tl, _vals = block.features(2)
            names = block.strings_at(no, nl)
            terms = block.strings_at(to, tl)
            expected = [feature_key(n, t) for n, t in zip(names, terms)]
            vocab, ids = block.dedup_keys(2, native_avro.DEDUP_FEATURE_KEYS)
            assert [vocab[i] for i in ids] == expected
            assert len(vocab) == len(set(expected))  # actually deduped

            _r, ko, kl, vo, vl = block.map_entries(3)
            keys = block.strings_at(ko, kl)
            vals = block.strings_at(vo, vl)
            kvocab, kids = block.dedup_keys(3, native_avro.DEDUP_MAP_KEYS)
            vvocab, vids = block.dedup_keys(3, native_avro.DEDUP_MAP_VALUES)
            assert [kvocab[i] for i in kids] == keys
            assert [vvocab[i] for i in vids] == vals

    def test_dedup_unsupported_field_raises(self):
        payload, ftypes = self._block_payload(n=2)
        with native_avro.decode_block(payload, 2, ftypes) as block:
            with pytest.raises(ValueError, match="dedup unsupported"):
                block.dedup_keys(1, native_avro.DEDUP_FEATURE_KEYS)  # a double col


class TestBackgroundOverlap:
    def test_background_task_result(self):
        task = pipeline.BackgroundTask(lambda: 41 + 1)
        assert task.result(timeout=10) == 42
        assert task.done()

    def test_background_task_reraises(self):
        def boom():
            raise RuntimeError("background boom")

        task = pipeline.BackgroundTask(boom)
        with pytest.raises(RuntimeError, match="background boom"):
            task.result(timeout=10)

    def test_background_task_timeout(self):
        gate = threading.Event()
        task = pipeline.BackgroundTask(gate.wait)
        with pytest.raises(TimeoutError):
            task.result(timeout=0.01)
        gate.set()
        task.result(timeout=10)

    def test_xla_warmup_idempotent(self):
        a = pipeline.start_xla_warmup()
        b = pipeline.start_xla_warmup()
        assert a is b
        assert a.result(timeout=300) is True

    def test_estimator_hook_delegates(self):
        from photon_ml_tpu.estimators.game_estimator import GameEstimator

        assert GameEstimator.warm_up_backend() is pipeline.start_xla_warmup()


class TestDownSamplerIdBoundary:
    """Satellite regression: global sample positions at or beyond 2**32 must
    keep distinct down-sampling draw keys (the old uint32 cast wrapped)."""

    def test_no_wrap_at_2_32(self):
        from photon_ml_tpu.sampling.down_sampler import per_sample_uniform

        ids = np.array([0, 5, 2**32, 2**32 + 5, 2**33], dtype=np.int64)
        draws = np.asarray(per_sample_uniform(11, 0, ids))
        assert draws.dtype == np.float32
        assert draws[2] != draws[0], "2**32 wrapped onto position 0"
        assert draws[3] != draws[1], "2**32+5 wrapped onto position 5"
        assert len(np.unique(draws)) == len(draws)

    def test_host_device_parity_below_boundary(self):
        import jax.numpy as jnp

        from photon_ml_tpu.sampling.down_sampler import per_sample_uniform

        ids = np.arange(64, dtype=np.int64)
        host = np.asarray(per_sample_uniform(11, 2, ids))
        device = np.asarray(
            per_sample_uniform(11, 2, jnp.arange(64, dtype=jnp.uint32))
        )
        np.testing.assert_array_equal(host, device)

    def test_down_sample_still_reproducible(self):
        from photon_ml_tpu.data.dataset import LabeledData
        from photon_ml_tpu.sampling.down_sampler import BinaryClassificationDownSampler

        rng = np.random.default_rng(3)
        n = 200
        data = LabeledData.build(
            rng.normal(size=(n, 4)), (rng.random(n) > 0.5).astype(np.float64)
        )
        a = BinaryClassificationDownSampler(0.3, seed=9).down_sample(data)
        b = BinaryClassificationDownSampler(0.3, seed=9).down_sample(data)
        np.testing.assert_array_equal(np.asarray(a.weights), np.asarray(b.weights))
