"""Test harness: force an 8-device CPU platform + float64.

Mirrors the reference's test strategy (SURVEY.md §4): the reference exercises
"distributed" behavior on a multi-core local[*] Spark; we exercise sharded jit /
shard_map code on a simulated 8-device CPU mesh via
--xla_force_host_platform_device_count. float64 gives numerical parity headroom for
optimizer convergence assertions (TPU production runs use f32/bf16).
"""

import os

# Force CPU: the ambient environment pins JAX_PLATFORMS=axon (the real TPU tunnel);
# unit tests must run on the simulated 8-device CPU platform regardless. jax may
# already be imported by a pytest plugin before this conftest, so set it through
# jax.config (effective until backends initialize) as well as the environment.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # older jax (< 0.5): XLA_FLAGS above already forces 8
    pass
jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: the suite is compile-dominated (hundreds of
# lax.while_loop optimizer programs), and programs are identical across runs —
# the second and later suite runs skip nearly all compiles. Safe to share: the
# cache key includes program, flags, and compiler version.
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get("PHOTON_XLA_CACHE", os.path.expanduser("~/.cache/photon_xla")),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture()
def rng(request):
    # Function-scoped and seeded per test: a session-scoped generator makes
    # every test's data depend on how many draws ran before it, so tests pass
    # or fail depending on execution order. Stable per-test seeding makes each
    # test reproducible in isolation and in any suite ordering.
    import zlib

    seed = zlib.crc32(request.node.nodeid.encode()) ^ 271828
    return np.random.default_rng(seed)


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 simulated devices, got {len(devs)}"
    return devs[:8]


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "chaos: crash-at-every-fault-point recovery sweeps (tier-1 adjacent; "
        "also run standalone via `pytest -m chaos`)",
    )
    config.addinivalue_line("markers", "slow: excluded from the tier-1 run")
