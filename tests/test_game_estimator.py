"""GameEstimator / GameTransformer tests: config grid expansion, warm-started
sweeps, partial retrain, scoring round trips. Mirrors GameEstimatorIntegTest /
GameTransformerIntegTest in the reference."""

import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu.data.game_data import GameInput
from photon_ml_tpu.estimators import (
    CoordinateConfiguration,
    FixedEffectDataConfiguration,
    GameEstimator,
    RandomEffectDataConfiguration,
    expand_game_configurations,
)
from photon_ml_tpu.evaluation import EvaluatorType, evaluator_for_type
from photon_ml_tpu.optimization.common import OptimizerConfig
from photon_ml_tpu.optimization.config import (
    GLMOptimizationConfiguration,
    RegularizationContext,
)
from photon_ml_tpu.transformers import GameTransformer
from photon_ml_tpu.types import RegularizationType, TaskType

OPT = GLMOptimizationConfiguration(
    optimizer_config=OptimizerConfig(max_iterations=60, tolerance=1e-8),
    regularization_context=RegularizationContext(RegularizationType.L2),
    regularization_weight=1.0,
)


def make_input(rng, n=800, d=4, n_users=8):
    w = rng.normal(size=d)
    bias = rng.normal(size=n_users) * 1.5
    X = rng.normal(size=(n, d))
    # deterministic round-robin entities: stable bucket shapes -> shared compiles
    users = np.arange(n) % n_users
    z = X @ w + bias[users]
    y = (z + 0.3 * rng.normal(size=n) > 0).astype(np.float64)
    uid = np.asarray([f"u{u}" for u in users], dtype=object)
    return GameInput(
        features={
            "global": X,
            "per-user": sp.csr_matrix(np.ones((n, 1))),
        },
        labels=y,
        id_columns={"userId": uid},
    )


def make_configs(reg_weights=()):
    return {
        "fixed": CoordinateConfiguration(
            data_config=FixedEffectDataConfiguration("global"),
            optimization_config=OPT,
            reg_weights=reg_weights,
        ),
        "per-user": CoordinateConfiguration(
            data_config=RandomEffectDataConfiguration("userId", "per-user"),
            optimization_config=OPT,
        ),
    }


def test_expand_game_configurations():
    configs = {
        "a": CoordinateConfiguration(
            data_config=FixedEffectDataConfiguration(),
            optimization_config=OPT,
            reg_weights=(0.1, 10.0, 1.0),
        ),
        "b": CoordinateConfiguration(
            data_config=FixedEffectDataConfiguration(),
            optimization_config=OPT,
            reg_weights=(2.0, 0.5),
        ),
    }
    sweep = expand_game_configurations(configs)
    assert len(sweep) == 6
    # strong -> weak regularization within each coordinate
    assert [c["a"].regularization_weight for c in sweep] == [10.0, 10.0, 1.0, 1.0, 0.1, 0.1]
    assert [c["b"].regularization_weight for c in sweep[:2]] == [2.0, 0.5]


def test_fit_and_select_best(rng):
    data = make_input(rng)
    train, val = data.select(np.arange(0, 550)), data.select(np.arange(550, 800))
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinate_configurations=make_configs(reg_weights=(10.0, 0.5)),
        n_iterations=2,
    )
    results = est.fit(train, validation_data=val)
    assert len(results) == 2  # two reg weights on the fixed coordinate
    assert [r.configuration["fixed"].regularization_weight for r in results] == [10.0, 0.5]
    for r in results:
        assert r.best_metric is not None and r.best_metric > 0.8
        assert r.evaluations is not None and "AUC" in r.evaluations
    best = est.select_best_model(results)
    assert best.best_metric == max(r.best_metric for r in results)


def test_fit_without_validation(rng):
    data = make_input(rng, n=300)
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinate_configurations=make_configs(),
        n_iterations=1,
    )
    results = est.fit(data)
    assert len(results) == 1
    assert results[0].best_metric is None
    assert est.select_best_model(results) is results[0]


def test_transformer_scores_and_metrics(rng):
    data = make_input(rng)
    train, test = data.select(np.arange(0, 600)), data.select(np.arange(600, 800))
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinate_configurations=make_configs(),
        n_iterations=2,
    )
    model = est.fit(train)[0].model
    transformer = GameTransformer(
        model=model, evaluators=[evaluator_for_type(EvaluatorType.AUC)]
    )
    scores, metrics = transformer.transform(test)
    assert scores.shape == (200,)
    assert metrics["AUC"] > 0.8
    # per-coordinate decomposition sums to the total (minus offsets here: zero)
    per = transformer.score_per_coordinate(test)
    np.testing.assert_allclose(per["fixed"] + per["per-user"], scores, rtol=1e-5)


def test_transformer_unseen_entities_score_fixed_only(rng):
    data = make_input(rng, n=400)
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinate_configurations=make_configs(),
        n_iterations=1,
    )
    model = est.fit(data)[0].model
    n_new = 50
    X_new = rng.normal(size=(n_new, 4))
    new_input = GameInput(
        features={"global": X_new, "per-user": sp.csr_matrix(np.ones((n_new, 1)))},
        id_columns={"userId": np.asarray(["stranger"] * n_new, dtype=object)},
    )
    per = GameTransformer(model=model).score_per_coordinate(new_input)
    np.testing.assert_array_equal(per["per-user"], np.zeros(n_new))
    assert np.abs(per["fixed"]).max() > 0


def test_partial_retrain_locks_coordinate(rng):
    data = make_input(rng, n=500)
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinate_configurations=make_configs(),
        n_iterations=1,
    )
    first = est.fit(data)[0].model

    est2 = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinate_configurations=make_configs(),
        n_iterations=2,
        partial_retrain_locked_coordinates=["fixed"],
    )
    results = est2.fit(data, initial_model=first)
    after = results[0].model.get_model("fixed")
    np.testing.assert_array_equal(
        np.asarray(after.model.coefficients.means),
        np.asarray(first.get_model("fixed").model.coefficients.means),
    )


def test_partial_retrain_requires_initial_model(rng):
    data = make_input(rng, n=200)
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinate_configurations=make_configs(),
        partial_retrain_locked_coordinates=["fixed"],
    )
    with pytest.raises(ValueError, match="initial_model"):
        est.fit(data)


def test_warm_start_chain_improves_or_matches(rng):
    """Sweep results should all be sane — the warm-start chain must not poison
    later configs (GameEstimator.fit:344-360 semantics)."""
    data = make_input(rng)
    train, val = data.select(np.arange(0, 550)), data.select(np.arange(550, 800))
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinate_configurations=make_configs(reg_weights=(100.0, 1.0, 0.01)),
        n_iterations=1,
    )
    results = est.fit(train, validation_data=val)
    assert len(results) == 3
    aucs = [r.best_metric for r in results]
    assert all(a > 0.75 for a in aucs)


def test_fe_storage_dtype_bf16_close_to_f32(rng):
    """Estimator-level bf16 feature storage: coefficients/metrics stay f32 and
    land near the full-precision fit (DenseDesignMatrix._mxu_dot)."""
    data = make_input(rng)
    train, val = data.select(np.arange(0, 550)), data.select(np.arange(550, 800))

    def fit(storage):
        est = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinate_configurations=make_configs(),
            n_iterations=2,
            fe_storage_dtype=storage,
        )
        return est.fit(train, validation_data=val)[0]

    import jax.numpy as jnp

    f32 = fit(None)
    bf16 = fit(jnp.bfloat16)
    coef = bf16.model.get_model("fixed").model.coefficients.means
    assert coef.dtype == jnp.float32
    assert bf16.best_metric == pytest.approx(f32.best_metric, abs=0.01)


def test_re_storage_dtype_requires_fused_pass():
    """re_storage_dtype is only consumed by the fused pass's
    build_sharded_game_data; accepting it elsewhere would be a silent no-op."""
    import jax.numpy as jnp
    import pytest as _pytest

    from photon_ml_tpu.estimators import (
        CoordinateConfiguration,
        FixedEffectDataConfiguration,
        GameEstimator,
    )

    cfgs = {
        "g": CoordinateConfiguration(
            data_config=FixedEffectDataConfiguration("g"),
            optimization_config=OPT,
        )
    }
    with _pytest.raises(ValueError, match="fused_pass"):
        GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinate_configurations=cfgs,
            re_storage_dtype=jnp.bfloat16,
        )
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinate_configurations=cfgs,
        re_storage_dtype=jnp.bfloat16,
        fused_pass=True,
    )
    assert est.re_storage_dtype == jnp.bfloat16


# -------------------------------------------------- GLM family matrix


def make_family_input(rng, task, n=600, d=4, n_users=8):
    """GLMix data whose labels follow the family's generative model."""
    w = rng.normal(size=d) * 0.6
    bias = rng.normal(size=n_users)
    X = rng.normal(size=(n, d))
    users = np.arange(n) % n_users
    z = X @ w + bias[users]
    task = TaskType(task)
    if task == TaskType.LOGISTIC_REGRESSION:
        y = (rng.random(n) < 1.0 / (1.0 + np.exp(-z))).astype(np.float64)
    elif task == TaskType.LINEAR_REGRESSION:
        y = z + 0.3 * rng.normal(size=n)
    elif task == TaskType.POISSON_REGRESSION:
        y = rng.poisson(np.exp(np.clip(z, -3.0, 2.0))).astype(np.float64)
    else:
        y = (z > 0).astype(np.float64)
    uid = np.asarray([f"u{u}" for u in users], dtype=object)
    return GameInput(
        features={
            "global": X,
            "per-user": sp.csr_matrix(np.ones((n, 1))),
        },
        labels=y,
        id_columns={"userId": uid},
    )


@pytest.mark.parametrize(
    "task",
    [
        TaskType.LOGISTIC_REGRESSION,
        TaskType.LINEAR_REGRESSION,
        TaskType.POISSON_REGRESSION,
        TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
    ],
)
def test_family_matrix_end_to_end(rng, task):
    """Every GLM family the reference trains (logistic, linear, Poisson,
    smoothed hinge) goes through the FULL GAME pipeline: fixed + random
    effect coordinate descent, the task's default validation evaluator,
    best-model selection, and fused-engine scoring of the result."""
    data = make_family_input(rng, task)
    train, val = data.select(np.arange(0, 420)), data.select(np.arange(420, 600))
    est = GameEstimator(
        task=task, coordinate_configurations=make_configs(), n_iterations=2
    )
    results = est.fit(train, validation_data=val)
    assert len(results) == 1
    r = results[0]
    assert r.best_metric is not None and np.isfinite(r.best_metric)
    for cid in ("fixed", "per-user"):
        m = r.best_model.get_model(cid)
        arrays = (
            [m.coeffs] if hasattr(m, "coeffs") else [m.model.coefficients.means]
        )
        for a in arrays:
            assert np.isfinite(np.asarray(a)).all(), cid
    # the trained family's model serves through the fused engine at one-ulp
    # tolerance: trained f32 coefficients against the x64 harness's f64
    # features promote the reduction, and eager/fused associate it
    # differently in the last f64 bit (same budget as test_serving's
    # mesh-path assert_parity; the same-dtype bitwise contract is pinned
    # there by the family_matrix engine tests)
    eager_t = GameTransformer(model=r.best_model, engine="eager")
    fused_t = GameTransformer(model=r.best_model, engine="fused")
    eager = eager_t.score(val, include_offsets=False)
    fused = fused_t.score(val, include_offsets=False)
    assert fused.dtype == eager.dtype
    np.testing.assert_allclose(fused, eager, rtol=5e-15, atol=1e-14)
    pc_e, pc_f = eager_t.score_per_coordinate(val), fused_t.score_per_coordinate(val)
    for cid in pc_e:
        np.testing.assert_allclose(
            pc_f[cid], pc_e[cid], rtol=5e-15, atol=1e-14, err_msg=cid
        )
    # the family's mean prediction applies its link (prediction sanity)
    if task == TaskType.POISSON_REGRESSION:
        from photon_ml_tpu.serving import get_engine

        assert (get_engine(r.best_model).predict(val) >= 0).all()
