"""End-to-end slice (SURVEY.md §7 step 3): synthetic Avro -> index map -> fixed-effect
training -> evaluators -> Avro model save/load round-trip.

Mirrors the reference's driver integration tests (GameTrainingDriverIntegTest:
full runs asserting AUC and saved-model equivalence).
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data import avro_io
from photon_ml_tpu.data.dataset import LabeledData
from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.data.matrix import SparseDesignMatrix, as_design_matrix
from photon_ml_tpu.data.readers import read_avro, read_libsvm, write_training_avro
from photon_ml_tpu.evaluation import EvaluatorType, evaluator_for_type
from photon_ml_tpu.evaluation.evaluators import MultiEvaluator, auc_roc, auc_pr, rmse
from photon_ml_tpu.io import load_glm_model, save_glm_model
from photon_ml_tpu.models import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.optimization.common import OptimizerConfig
from photon_ml_tpu.optimization.config import (
    GLMOptimizationConfiguration,
    RegularizationContext,
)
from photon_ml_tpu.optimization.problem import GLMOptimizationProblem
from photon_ml_tpu.types import (
    OptimizerType,
    RegularizationType,
    TaskType,
    VarianceComputationType,
)


def synthetic_records(rng, n=400, d=6):
    """TrainingExampleAvro-shaped records with a known generating model."""
    w = rng.normal(size=d)
    recs = []
    X = np.zeros((n, d))
    for i in range(n):
        nz = rng.choice(d, size=rng.integers(2, d + 1), replace=False)
        feats = []
        for j in nz:
            v = float(rng.normal())
            X[i, j] = v
            feats.append({"name": f"f{j}", "term": "", "value": v})
        z = X[i] @ w + 0.5 * rng.normal()
        recs.append(
            {
                "uid": str(i),
                "label": float(z > 0),
                "features": feats,
                "metadataMap": {"userId": f"u{i % 7}"},
                "weight": 1.0,
                "offset": None,
            }
        )
    return recs, w


# ------------------------------------------------------------------ avro codec


def test_avro_container_roundtrip(rng, tmp_path):
    recs, _ = synthetic_records(rng, n=50)
    path = str(tmp_path / "data.avro")
    write_training_avro(path, recs)
    back = list(avro_io.read_container(path))
    assert len(back) == 50
    assert back[0]["uid"] == "0"
    assert back[3]["features"] == recs[3]["features"]
    assert back[7]["metadataMap"] == recs[7]["metadataMap"]
    # weight survives the union encoding
    assert back[11]["weight"] == 1.0


def test_avro_null_codec_roundtrip(rng, tmp_path):
    recs, _ = synthetic_records(rng, n=5)
    path = str(tmp_path / "data.avro")
    avro_io.write_container(path, avro_io.TRAINING_EXAMPLE_SCHEMA, recs, codec="null")
    assert list(avro_io.read_container(path))[2]["label"] == recs[2]["label"]


def test_avro_multiblock(rng, tmp_path):
    recs, _ = synthetic_records(rng, n=100)
    path = str(tmp_path / "data.avro")
    avro_io.write_container(path, avro_io.TRAINING_EXAMPLE_SCHEMA, recs, block_count=7)
    assert len(list(avro_io.read_container(path))) == 100


# ------------------------------------------------------------------ index map


def test_index_map_roundtrip(tmp_path):
    im = IndexMap.build([feature_key("b"), feature_key("a", "t1"), feature_key("b")])
    assert im.size == 3  # 2 distinct + intercept
    assert im.intercept_index is not None
    assert im.get_index(feature_key("zzz")) == -1
    p = str(tmp_path / "imap.npz")
    im.save(p)
    im2 = IndexMap.load(p)
    assert im2.keys() == im.keys()
    assert im2.intercept_index == im.intercept_index


# ------------------------------------------------------------------ readers


def test_read_avro_builds_matrix(rng, tmp_path):
    recs, _ = synthetic_records(rng, n=30)
    path = str(tmp_path / "train.avro")
    write_training_avro(path, recs)
    ds, imap = read_avro(path, id_tags=["userId"])
    assert ds.n == 30 and ds.dim == imap.size
    assert imap.intercept_index is not None
    np.testing.assert_array_equal(
        np.asarray(ds.X[:, imap.intercept_index].todense()).ravel(), np.ones(30)
    )
    assert ds.id_columns["userId"][0] == "u0"
    # feature values land in the right columns
    j = imap.get_index(feature_key("f0"))
    rec_vals = {int(r["uid"]): {f["name"]: f["value"] for f in r["features"]} for r in recs}
    for i in range(30):
        expect = rec_vals[i].get("f0", 0.0)
        assert ds.X[i, j] == pytest.approx(expect)


def test_read_libsvm(tmp_path):
    p = tmp_path / "a1a.txt"
    p.write_text("+1 3:1 11:0.5\n-1 3:1 4:2\n+1 11:1\n")
    ds, imap = read_libsvm(str(p))
    assert ds.n == 3
    np.testing.assert_array_equal(ds.labels, [1.0, 0.0, 1.0])
    j = imap.get_index(feature_key("3"))
    assert ds.X[0, j] == 1.0 and ds.X[1, j] == 1.0 and ds.X[2, j] == 0.0


# ------------------------------------------------------------------ evaluators


def test_auc_known_value():
    scores = [0.1, 0.4, 0.35, 0.8]
    labels = [0, 0, 1, 1]
    # pairs: (0.35 vs 0.1 ok), (0.35 vs 0.4 bad), (0.8 vs both ok) -> 3/4
    assert auc_roc(scores, labels) == pytest.approx(0.75)
    assert auc_roc([1.0, 1.0], [1, 1]) != auc_roc([1.0, 1.0], [1, 1])  # nan


def test_auc_ties():
    assert auc_roc([0.5, 0.5, 0.5, 0.5], [1, 0, 1, 0]) == pytest.approx(0.5)


def test_rmse_and_aupr():
    assert rmse([1.0, 2.0], [0.0, 4.0]) == pytest.approx(np.sqrt((1 + 4) / 2))
    assert auc_pr([0.9, 0.1], [1, 0]) == pytest.approx(1.0)


def test_multi_evaluator_groups():
    ev = MultiEvaluator(evaluator_for_type(EvaluatorType.AUC), "userId")
    scores = [0.9, 0.1, 0.8, 0.2, 0.5]
    labels = [1, 0, 0, 1, 1]
    groups = ["a", "a", "b", "b", "c"]  # a: auc 1.0, b: auc 0.0, c: single-class -> nan
    v = ev.evaluate_grouped(scores, labels, None, groups)
    assert v == pytest.approx(0.5)


# ------------------------------------------------------------------ training E2E


@pytest.mark.parametrize(
    "task,opt",
    [
        (TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS),
        (TaskType.LOGISTIC_REGRESSION, OptimizerType.TRON),
        (TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM, OptimizerType.LBFGS),
    ],
)
def test_train_evaluate_save_load(rng, tmp_path, task, opt):
    recs, _ = synthetic_records(rng, n=400)
    train_path = str(tmp_path / "train.avro")
    write_training_avro(train_path, recs)
    ds, imap = read_avro(train_path)

    data = LabeledData.build(
        SparseDesignMatrix.from_scipy(ds.X, dtype=jnp.float64),
        ds.labels, ds.offsets, ds.weights,
    )
    problem = GLMOptimizationProblem(
        task=task,
        configuration=GLMOptimizationConfiguration(
            optimizer_config=OptimizerConfig(optimizer_type=opt, max_iterations=100, tolerance=1e-9),
            regularization_context=RegularizationContext(RegularizationType.L2),
            regularization_weight=1.0,
        ),
    )
    model, result = problem.run(data)
    assert bool(result.converged)

    scores = np.asarray(model.score(data))
    auc = auc_roc(scores, ds.labels)
    assert auc > 0.85, f"AUC too low: {auc}"

    # save / load round-trip preserves predictions
    mpath = str(tmp_path / "model" / "part-00000.avro")
    save_glm_model(mpath, model, imap, model_id="global")
    loaded = load_glm_model(mpath, imap, dtype=jnp.float64)
    assert loaded.task == TaskType(task)
    np.testing.assert_allclose(
        np.asarray(loaded.score(data)), scores, atol=1e-12
    )


def test_elastic_net_owlqn_end_to_end(rng, tmp_path):
    recs, _ = synthetic_records(rng, n=300)
    ds, imap = _records_dataset(rng, recs, tmp_path)
    data = LabeledData.build(
        SparseDesignMatrix.from_scipy(ds.X, dtype=jnp.float64), ds.labels, ds.offsets, ds.weights
    )
    problem = GLMOptimizationProblem(
        task=TaskType.LOGISTIC_REGRESSION,
        configuration=GLMOptimizationConfiguration(
            optimizer_config=OptimizerConfig(optimizer_type=OptimizerType.OWLQN, max_iterations=200),
            regularization_context=RegularizationContext(RegularizationType.ELASTIC_NET, 0.5),
            regularization_weight=2.0,
        ),
    )
    model, result = problem.run(data)
    scores = np.asarray(model.score(data))
    assert auc_roc(scores, ds.labels) > 0.8


def _records_dataset(rng, recs, tmp_path):
    path = str(tmp_path / "t.avro")
    write_training_avro(path, recs)
    return read_avro(path)


def test_variance_computation_matches_closed_form(rng):
    """SIMPLE/FULL variances vs the analytic Gaussian (linear regression):
    the reference checks Hessian-based variances against closed form
    (DistributedOptimizationProblemIntegTest)."""
    n, d = 200, 4
    X = rng.normal(size=(n, d))
    y = X @ np.array([1.0, -1.0, 0.5, 2.0]) + 0.1 * rng.normal(size=n)
    data = LabeledData.build(X, y)
    for vtype in (VarianceComputationType.SIMPLE, VarianceComputationType.FULL):
        problem = GLMOptimizationProblem(
            task=TaskType.LINEAR_REGRESSION,
            configuration=GLMOptimizationConfiguration(
                optimizer_config=OptimizerConfig(max_iterations=200, tolerance=1e-12)
            ),
            variance_computation=vtype,
        )
        model, _ = problem.run(data)
        H = np.asarray(X.T @ X)
        if vtype == VarianceComputationType.SIMPLE:
            expect = 1.0 / np.diag(H)
        else:
            expect = np.diag(np.linalg.inv(H))
        np.testing.assert_allclose(
            np.asarray(model.coefficients.variances), expect, rtol=1e-6
        )


def test_tron_rejects_hinge():
    with pytest.raises(ValueError, match="twice-differentiable"):
        GLMOptimizationProblem(
            task=TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
            configuration=GLMOptimizationConfiguration(
                optimizer_config=OptimizerConfig(optimizer_type=OptimizerType.TRON)
            ),
        )


# ------------------------------------------------- regression: review findings


def test_int_labels_train_cleanly(rng):
    X = rng.normal(size=(60, 3))
    y = (X @ np.array([1.0, -1.0, 0.5]) > 0).astype(int)  # int labels
    data = LabeledData.build(X, y)
    problem = GLMOptimizationProblem(
        task=TaskType.LOGISTIC_REGRESSION,
        configuration=GLMOptimizationConfiguration(
            optimizer_config=OptimizerConfig(max_iterations=50),
            regularization_context=RegularizationContext(RegularizationType.L2),
            regularization_weight=0.5,
        ),
    )
    model, result = problem.run(data)
    assert bool(result.converged)


def test_explicit_intercept_not_double_counted(rng, tmp_path):
    from photon_ml_tpu.types import InputColumnsNames

    recs = [
        {
            "uid": "0",
            "label": 1.0,
            "features": [
                {"name": InputColumnsNames.INTERCEPT_NAME, "term": "", "value": 1.0},
                {"name": "f0", "term": "", "value": 2.0},
            ],
            "metadataMap": None,
            "weight": None,
            "offset": None,
        }
    ]
    path = str(tmp_path / "i.avro")
    write_training_avro(path, recs)
    ds, imap = read_avro(path)
    assert ds.X[0, imap.intercept_index] == 1.0  # not 2.0


def test_weighted_auc():
    scores = [0.9, 0.8, 0.2, 0.1]
    labels = [1, 0, 1, 0]
    # unweighted: pairs (s_p, s_n): (0.9>0.8), (0.9>0.1), (0.2<0.8), (0.2>0.1) -> 3/4
    assert auc_roc(scores, labels) == pytest.approx(0.75)
    # zero weight on the bad positive removes its pairs -> perfect ranking
    assert auc_roc(scores, labels, [1.0, 1.0, 0.0, 1.0]) == pytest.approx(1.0)
    # weighted ties
    assert auc_roc([0.5, 0.5], [1, 0], [3.0, 7.0]) == pytest.approx(0.5)
