"""Fused serving engine: parity with the eager GameTransformer path, batch
bucketing / retrace behavior, engine caching, and the zero-coordinate
regression (ISSUE 1).

Parity is asserted BITWISE (np.testing.assert_array_equal + dtype equality)
against the eager per-coordinate path on the three BASELINE workload shapes:
fixed-effect-only logistic (config #1), fixed-effect linear/Poisson (config
#2's scoring surface), and the 3-coordinate GLMix shape (config #3) — plus a
RandomProjector (RANDOM_PROJECTION) random-effect coordinate and the
mesh-placed path.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

from photon_ml_tpu.data.game_data import GameInput
from photon_ml_tpu.models.game import FixedEffectModel, GameModel, RandomEffectModel
from photon_ml_tpu.models.glm import (
    Coefficients,
    LinearRegressionModel,
    LogisticRegressionModel,
    PoissonRegressionModel,
)
from photon_ml_tpu.serving import (
    GameServingEngine,
    clear_engine_cache,
    get_engine,
    model_fingerprint,
)
from photon_ml_tpu.transformers import GameTransformer
from photon_ml_tpu.types import TaskType


@pytest.fixture(autouse=True)
def _fresh_engine_cache():
    clear_engine_cache()
    yield
    clear_engine_cache()


def fixed_model(rng, d=6, cls=LogisticRegressionModel, shard="global"):
    means = jnp.asarray(rng.normal(size=d))
    return FixedEffectModel(model=cls(Coefficients(means=means)), feature_shard_id=shard)


def random_model(rng, re_type, n_entities, d=5, k_max=3, shard="re_shard"):
    """Per-entity models over random column subsets of a [*, d] shard — the
    loaded-from-disk layout (slot order = surviving columns)."""
    proj = np.full((n_entities, k_max), -1, dtype=np.int32)
    coeffs = np.zeros((n_entities, k_max))
    for i in range(n_entities):
        k = int(rng.integers(1, k_max + 1))
        cols = np.sort(rng.choice(d, size=k, replace=False))
        proj[i, :k] = cols
        coeffs[i, :k] = rng.normal(size=k)
    return RandomEffectModel(
        re_type=re_type,
        feature_shard_id=shard,
        task=TaskType.LOGISTIC_REGRESSION,
        entity_ids=tuple(f"e{i}" for i in range(n_entities)),
        coeffs=jnp.asarray(coeffs),
        proj_indices=jnp.asarray(proj),
    )


def glmix_input(rng, n=137, d=6, d_re=5, n_users=10, n_items=4, with_items=True):
    """The BASELINE config #3 shape: dense fixed shard + sparse RE shard, with
    ids that include entities the models never saw and columns outside every
    per-entity projection."""
    users = np.asarray(
        [f"e{i}" for i in rng.integers(0, n_users + 3, size=n)], dtype=object
    )
    ids = {"userId": users}
    if with_items:
        ids["itemId"] = np.asarray(
            [f"e{i}" for i in rng.integers(0, n_items + 2, size=n)], dtype=object
        )
    re_dense = rng.normal(size=(n, d_re))
    re_dense[rng.random(size=re_dense.shape) < 0.4] = 0.0  # genuinely sparse
    return GameInput(
        features={
            "global": rng.normal(size=(n, d)),
            "re_shard": sp.csr_matrix(re_dense),
        },
        labels=(rng.random(n) > 0.5).astype(np.float64),
        offsets=rng.normal(size=n),
        id_columns=ids,
    )


def assert_parity(model, data, mesh=None, exact=True):
    """Fused engine output must match the eager path, same dtype. Host paths
    are BITWISE; mesh paths compare at one-ulp tolerance (exact=False) because
    differently partitioned program shapes may associate a reduction
    differently in the last bit."""

    def check(a, b):
        if exact:
            np.testing.assert_array_equal(a, b)
        else:
            np.testing.assert_allclose(a, b, rtol=5e-15, atol=1e-14)

    eager = GameTransformer(model=model, engine="eager", mesh=mesh)
    fused = GameTransformer(model=model, engine="fused", mesh=mesh)
    for include_offsets in (True, False):
        se = eager.score(data, include_offsets=include_offsets)
        sf = fused.score(data, include_offsets=include_offsets)
        assert sf.dtype == se.dtype
        assert sf.shape == se.shape
        check(sf, se)
    pe = eager.score_per_coordinate(data)
    pf = fused.score_per_coordinate(data)
    assert list(pf) == list(pe)
    for cid in pe:
        assert pf[cid].dtype == pe[cid].dtype, cid
        check(pf[cid], pe[cid])


# --------------------------------------------------------------------------
# parity on the BASELINE shapes
# --------------------------------------------------------------------------


def test_parity_fixed_effect_only_logistic(rng):
    """BASELINE config #1: fixed-effect-only logistic regression."""
    model = GameModel(models={"fixed": fixed_model(rng)})
    assert_parity(model, glmix_input(rng, with_items=False))


@pytest.mark.parametrize("cls", [LinearRegressionModel, PoissonRegressionModel])
def test_parity_fixed_effect_linear_poisson(rng, cls):
    """BASELINE config #2's scoring surface: linear / Poisson fixed effects
    (raw scores are task-independent margins; predict() differs by link)."""
    model = GameModel(models={"fixed": fixed_model(rng, cls=cls)})
    assert_parity(model, glmix_input(rng, with_items=False))


def test_parity_glmix_three_coordinates(rng):
    """BASELINE config #3: fixed + per-user + per-item random effects."""
    model = GameModel(
        models={
            "fixed": fixed_model(rng),
            "per-user": random_model(rng, "userId", 10),
            "per-item": random_model(rng, "itemId", 4),
        }
    )
    assert_parity(model, glmix_input(rng))


def test_parity_mixed_precision_coordinates(rng):
    """f64 fixed effect + f32 random-effect table: per-coordinate dtypes must
    survive the fused path (no stack promotion)."""
    re = random_model(rng, "userId", 10)
    re = RandomEffectModel(
        re_type=re.re_type,
        feature_shard_id=re.feature_shard_id,
        task=re.task,
        entity_ids=re.entity_ids,
        coeffs=re.coeffs.astype(jnp.float32),
        proj_indices=re.proj_indices,
    )
    model = GameModel(models={"fixed": fixed_model(rng), "per-user": re})
    data = glmix_input(rng, with_items=False)
    assert_parity(model, data)
    per = GameTransformer(model=model).score_per_coordinate(data)
    assert per["per-user"].dtype == np.float32
    assert per["fixed"].dtype == np.float64


def test_parity_integer_offsets(rng):
    """Integer offsets promote differently under jnp (f32+i64 -> f32) than
    numpy (-> f64): the engine must take the host add and match eager."""
    means = jnp.asarray(rng.normal(size=6).astype(np.float32))
    model = GameModel(
        models={
            "fixed": FixedEffectModel(
                model=LogisticRegressionModel(Coefficients(means=means)),
                feature_shard_id="global",
            )
        }
    )
    n = 21
    data = GameInput(
        features={"global": rng.normal(size=(n, 6)).astype(np.float32)},
        offsets=rng.integers(-3, 3, size=n),
    )
    assert_parity(model, data)


def test_parity_projected_random_effect(rng):
    """A RANDOM_PROJECTION coordinate: the engine must run the model's own
    projector at request time, exactly like the eager dataset build."""
    from photon_ml_tpu.data.projector import ProjectorConfig, ProjectorType, make_projector

    d_re, kp = 7, 3
    projector = make_projector(
        ProjectorConfig(
            projector_type=ProjectorType.RANDOM_PROJECTION, projected_dim=kp, seed=7
        ),
        original_dim=d_re,
        intercept_index=0,
    )
    E = 6
    k_cols = projector.projected_dim
    model = GameModel(
        models={
            "fixed": fixed_model(rng),
            "per-user": RandomEffectModel(
                re_type="userId",
                feature_shard_id="re_shard",
                task=TaskType.LOGISTIC_REGRESSION,
                entity_ids=tuple(f"e{i}" for i in range(E)),
                coeffs=jnp.asarray(rng.normal(size=(E, k_cols))),
                proj_indices=jnp.asarray(
                    np.tile(np.arange(k_cols, dtype=np.int32), (E, 1))
                ),
                projector=projector,
            ),
        }
    )
    assert_parity(model, glmix_input(rng, d_re=d_re, n_users=E, with_items=False))


def test_parity_mesh_placed(rng, eight_devices):
    """1-D mesh scoring: fused-on-mesh matches eager-on-mesh (one-ulp
    tolerance: the partitioned programs tile the reductions differently) and
    the host fused path; n=137 is not divisible by 8 so the padded-sample
    trim is genuinely exercised."""
    from photon_ml_tpu.parallel.mesh import make_mesh

    model = GameModel(
        models={
            "fixed": fixed_model(rng),
            "per-user": random_model(rng, "userId", 10),
        }
    )
    data = glmix_input(rng, with_items=False)
    mesh = make_mesh(8)
    assert_parity(model, data, mesh=mesh, exact=False)
    host = GameTransformer(model=model).score(data)
    np.testing.assert_allclose(
        GameTransformer(model=model, mesh=mesh).score(data), host,
        rtol=5e-15, atol=1e-14,
    )


def test_transform_metrics_parity(rng):
    model = GameModel(
        models={
            "fixed": fixed_model(rng),
            "per-user": random_model(rng, "userId", 10),
        }
    )
    data = glmix_input(rng, with_items=False)
    s_e, m_e = GameTransformer(model=model, engine="eager", evaluators=["AUC"]).transform(data)
    s_f, m_f = GameTransformer(model=model, evaluators=["AUC"]).transform(data)
    np.testing.assert_array_equal(s_f, s_e)
    assert m_f["AUC"] == m_e["AUC"]


def test_predict_applies_link_on_device(rng):
    model = GameModel(models={"fixed": fixed_model(rng)})
    data = glmix_input(rng, with_items=False)
    eng = get_engine(model)
    margins = eng.score(data, include_offsets=True)
    np.testing.assert_allclose(
        eng.predict(data), 1.0 / (1.0 + np.exp(-margins)), rtol=1e-12
    )


# --------------------------------------------------------------------------
# bucketing, retraces, engine cache
# --------------------------------------------------------------------------


def test_batch_bucketing_no_retrace_same_bucket(rng):
    """Second request in the same power-of-two bucket must NOT retrace; the
    next bucket up compiles exactly one new program (trace-counter fixture)."""
    model = GameModel(
        models={
            "fixed": fixed_model(rng),
            "per-user": random_model(rng, "userId", 10),
        }
    )
    eng = get_engine(model)
    assert eng.bucket(50) == 64 and eng.bucket(60) == 64 and eng.bucket(100) == 128

    def req(n):
        # dense RE shard (no zeros): constant per-row nnz, so only the batch
        # axis varies between requests — the serving steady state
        return GameInput(
            features={
                "global": rng.normal(size=(n, 6)),
                "re_shard": sp.csr_matrix(rng.normal(size=(n, 5)) + 10.0),
            },
            id_columns={
                "userId": np.asarray([f"e{i % 10}" for i in range(n)], dtype=object)
            },
        )

    eng.score(req(50))
    warm = eng.trace_count
    assert warm >= 1
    eng.score(req(60))  # same bucket: cache hit, no retrace
    assert eng.trace_count == warm
    eng.score(req(100))  # next bucket: exactly one new trace
    assert eng.trace_count == warm + 1
    eng.score(req(128))
    assert eng.trace_count == warm + 1


def test_nnz_width_bucketing_no_retrace(rng):
    """Requests whose max row nnz varies inside one pow2 width bucket must not
    retrace; crossing the width bucket compiles exactly one new program."""
    model = GameModel(models={"per-user": random_model(rng, "userId", 6, d=20)})
    eng = get_engine(model)

    def req(nnz_per_row):
        n = 32
        dense = np.zeros((n, 20))
        for i in range(n):
            cols = rng.choice(20, size=nnz_per_row, replace=False)
            dense[i, cols] = rng.normal(size=nnz_per_row) + 5.0
        return GameInput(
            features={"re_shard": sp.csr_matrix(dense)},
            id_columns={
                "userId": np.asarray([f"e{i % 6}" for i in range(n)], dtype=object)
            },
        )

    eng.score(req(5))  # W=5 -> width bucket 8
    warm = eng.trace_count
    eng.score(req(7))  # W=7 -> still 8: no retrace
    eng.score(req(3))  # W=3 -> 4: narrower widths do re-bucket...
    eng.score(req(8))  # ...and 8 again is a cache hit
    assert eng.trace_count == warm + 1  # only the W->4 program was new
    eng.score(req(12))  # W=12 -> 16: one new program
    assert eng.trace_count == warm + 2


def test_wide_fe_dense_request_routes_through_sparse_path(rng, monkeypatch):
    """Wide-K fixed-effect routing: a dense-container request at
    K >= FE_SPARSE_MIN_COLS scores through the per-sample (cols, vals) view —
    BITWISE the CSR-container path (same prepared batch, same program) — and
    agrees with the small-K dense matvec to the f32 value-storage tolerance
    (the two kernels' reductions associate differently: FMA-contracted
    [B, K] matvec vs the width-bucketed row reduce)."""
    from photon_ml_tpu.serving import engine as engine_mod

    d, n, nnz = 32, 40, 5
    model = GameModel(models={"fixed": fixed_model(rng, d=d)})
    dense = np.zeros((n, d))
    for i in range(n):
        cols = rng.choice(d, size=nnz, replace=False)
        dense[i, cols] = rng.normal(size=nnz)
    req_dense = GameInput(features={"global": dense})
    req_csr = GameInput(features={"global": sp.csr_matrix(dense)})

    # both-fit shape, default cutoff: the dense [B, K] kernel serves this K
    eng = GameServingEngine(model)
    assert "values" in eng._prepare(req_dense)[0]["coord:fixed"]
    s_dense = eng.score(req_dense, include_offsets=False)
    s_csr = eng.score(req_csr, include_offsets=False)

    # force the routing cutoff under K: the dense container now prepares the
    # SAME batch the CSR container does — width = the nnz bucket, no [B, K]
    monkeypatch.setattr(engine_mod, "FE_SPARSE_MIN_COLS", 8)
    eng_routed = GameServingEngine(model)
    fe = eng_routed._prepare(req_dense)[0]["coord:fixed"]
    assert "values" not in fe
    assert fe["cols"].shape[1] == 8  # width_bucket(5), not K=32
    s_routed = eng_routed.score(req_dense, include_offsets=False)

    # container invariance is BITWISE: routed-dense == sparse-CSR exactly
    assert s_routed.dtype == s_csr.dtype
    np.testing.assert_array_equal(s_routed, s_csr)
    # vs the dense kernel: f32 value storage + reduction order, not bitwise
    # (a few f32 ulps accumulated over the row's nnz entries)
    assert s_routed.dtype == s_dense.dtype
    np.testing.assert_allclose(s_routed, s_dense, rtol=1e-5, atol=1e-8)


def test_wide_fe_dense_request_routes_by_default_at_wide_k(rng):
    """At K past the default cutoff no monkeypatching is needed: the routing
    engages on its own and the device batch never holds a [B, K] buffer."""
    from photon_ml_tpu.serving.engine import FE_SPARSE_MIN_COLS

    d, n, nnz = FE_SPARSE_MIN_COLS, 16, 6
    model = GameModel(models={"fixed": fixed_model(rng, d=d)})
    dense = np.zeros((n, d))
    for i in range(n):
        cols = rng.choice(d, size=nnz, replace=False)
        dense[i, cols] = rng.normal(size=nnz)
    eng = GameServingEngine(model)
    fe = eng._prepare(GameInput(features={"global": dense}))[0]["coord:fixed"]
    assert "values" not in fe and fe["cols"].shape[1] == 8  # nnz bucket, not K
    s_routed = eng.score(GameInput(features={"global": dense}), include_offsets=False)
    s_csr = eng.score(
        GameInput(features={"global": sp.csr_matrix(dense)}), include_offsets=False
    )
    np.testing.assert_array_equal(s_routed, s_csr)


def test_entity_id_dtype_mismatch_degrades_like_eager(rng):
    """Integer-entity model served string ids must score those rows 0 (the
    eager dict-lookup miss), not crash in searchsorted."""
    E, d = 5, 4
    model = GameModel(
        models={
            "per-user": RandomEffectModel(
                re_type="userId",
                feature_shard_id="re_shard",
                task=TaskType.LOGISTIC_REGRESSION,
                entity_ids=tuple(range(E)),
                coeffs=jnp.asarray(rng.normal(size=(E, d))),
                proj_indices=jnp.asarray(np.tile(np.arange(d, dtype=np.int32), (E, 1))),
            )
        }
    )
    n = 11
    data = GameInput(
        features={"re_shard": sp.csr_matrix(rng.normal(size=(n, d)))},
        id_columns={"userId": np.asarray([f"u{i}" for i in range(n)], dtype=object)},
    )
    out = GameTransformer(model=model).score(data, include_offsets=False)
    np.testing.assert_array_equal(out, np.zeros(n))
    # matching int ids still resolve through the same engine
    data_int = GameInput(
        features={"re_shard": sp.csr_matrix(rng.normal(size=(n, d)))},
        id_columns={"userId": np.arange(n) % E},
    )
    assert np.abs(GameTransformer(model=model).score(data_int, include_offsets=False)).max() > 0


def test_get_engine_content_keyed_cache(rng):
    m1 = GameModel(models={"fixed": fixed_model(rng)})
    # same content -> same fingerprint -> same engine instance
    m2 = GameModel(models={"fixed": m1.models["fixed"]})
    assert get_engine(m1) is get_engine(m2)
    assert model_fingerprint(m1) == model_fingerprint(m2)
    # different coefficients -> different engine
    m3 = GameModel(models={"fixed": fixed_model(rng)})
    assert model_fingerprint(m3) != model_fingerprint(m1)
    assert get_engine(m3) is not get_engine(m1)


def test_engine_serves_2d_mesh_fused_via_capability_probe(rng, eight_devices):
    """PR 10: 2-D training meshes serve FUSED — tables replicate, batches
    shard along the data axis; ``mesh_capable`` is the one owner of the
    fused-vs-eager decision (no construction try/except anywhere)."""
    from photon_ml_tpu.parallel.feature_sharded import make_mesh2
    from photon_ml_tpu.parallel.mesh import make_mesh

    mesh2 = make_mesh2(n_data=4, n_model=2)
    assert GameServingEngine.mesh_capable(None)
    assert GameServingEngine.mesh_capable(make_mesh(8))
    assert GameServingEngine.mesh_capable(mesh2)
    model = GameModel(models={"fixed": fixed_model(rng)})
    data = glmix_input(rng, with_items=False)
    host = GameTransformer(model=model).score(data)
    t2 = GameTransformer(model=model, mesh=mesh2)
    # the transformer picks the FUSED path through the probe
    eng = t2._serving_engine()
    assert eng is not None
    # batch padding rounds to the BATCH axis (4), not the device count (8)
    assert eng.bucket(5) == max(eng.min_batch_pad, 4)
    np.testing.assert_array_equal(t2.score(data), host)

    class _NotAMesh:
        axis_names = ()

    assert not GameServingEngine.mesh_capable(_NotAMesh())
    with pytest.raises(ValueError, match="mesh_capable"):
        GameServingEngine(model, mesh=_NotAMesh())
    # the transformer falls back eagerly (once-logged) on an incapable mesh
    t_bad = GameTransformer(model=model, mesh=_NotAMesh())
    assert t_bad._serving_engine() is None


# --------------------------------------------------------------------------
# zero-coordinate regression (ISSUE 1 satellite)
# --------------------------------------------------------------------------


@pytest.mark.parametrize("engine", ["eager", "fused"])
def test_zero_coordinate_model_scores_offsets_shape(rng, engine):
    """Offsets-only scoring on an empty GameModel must return a [N] array,
    not a 0.0 scalar (np.sum([], axis=0) regression)."""
    n = 17
    offsets = rng.normal(size=n)
    data = GameInput(
        features={"global": rng.normal(size=(n, 3))},
        offsets=offsets,
    )
    t = GameTransformer(model=GameModel(models={}), engine=engine)
    scored = t.score(data)
    assert scored.shape == (n,)
    assert scored.dtype == np.float64  # numpy zeros + promotion, both engines
    np.testing.assert_array_equal(scored, offsets)
    raw = t.score(data, include_offsets=False)
    assert raw.shape == (n,)
    np.testing.assert_array_equal(raw, np.zeros(n))
    assert t.score_per_coordinate(data) == {}


def test_coordinate_named_offsets_does_not_collide(rng):
    """Coordinate ids are user config strings; one literally named "offsets"
    must not collide with the engine's reserved offsets batch entry."""
    model = GameModel(models={"offsets": fixed_model(rng)})
    assert_parity(model, glmix_input(rng, with_items=False))


def test_unseen_entities_and_columns_score_zero(rng):
    """Entities without a model and columns outside an entity's projection
    contribute exactly 0 through the fused path (aligned_to semantics)."""
    model = GameModel(models={"per-user": random_model(rng, "userId", 3, d=5)})
    n = 9
    data = GameInput(
        features={"re_shard": sp.csr_matrix(rng.normal(size=(n, 5)))},
        id_columns={"userId": np.asarray(["nobody"] * n, dtype=object)},
    )
    np.testing.assert_array_equal(
        GameTransformer(model=model).score(data, include_offsets=False), np.zeros(n)
    )


# ----------------------------------------------------- runtime sync discipline
# PR 1's "zero retraces after warmup" was prose + an engine-local counter;
# these tests enforce it with the process-wide runtime guard
# (photon_ml_tpu/analysis/runtime_guard.py): introducing a post-warmup retrace
# ANYWHERE in the serving path — or, on accelerator backends, an implicit
# device->host transfer — makes this file fail.


def _guard_model_and_req(rng):
    model = GameModel(
        models={"fixed": fixed_model(rng), "per-user": random_model(rng, "userId", 10)}
    )

    def req(n):
        return GameInput(
            features={
                "global": rng.normal(size=(n, 6)),
                "re_shard": sp.csr_matrix(rng.normal(size=(n, 5)) + 10.0),
            },
            id_columns={
                "userId": np.asarray([f"e{i % 10}" for i in range(n)], dtype=object)
            },
        )

    return get_engine(model), req


def test_steady_state_serving_under_sync_discipline(rng):
    """The serving contract, enforced: a warmed engine scores a same-bucket
    request stream with ZERO jaxpr traces and no unnamed d->h transfer."""
    from photon_ml_tpu.analysis.runtime_guard import sync_discipline

    eng, req = _guard_model_and_req(rng)
    eng.score(req(50))  # warmup compile OUTSIDE the guard
    with sync_discipline(what="serving steady state") as region:
        for n in (50, 60, 64, 57):  # all pad into the 64 bucket
            eng.score(req(n))
    assert region.traces == 0


def test_post_warmup_retrace_fails_the_guard(rng):
    """A bucket-crossing request is a compile-cache miss: the guard must turn
    it into a hard failure rather than a silently slower request."""
    from photon_ml_tpu.analysis.runtime_guard import RetraceError, sync_discipline

    eng, req = _guard_model_and_req(rng)
    eng.score(req(50))
    with pytest.raises(RetraceError, match="jaxpr trace"):
        with sync_discipline(what="serving steady state"):
            eng.score(req(100))  # 128 bucket: must compile -> guard trips


# ------------------------------------------------- concurrent serving safety
# The serving frontend runs dispatch on its own thread while hot-swap warm-up
# compiles on another; these tests pin the engine-level guarantees that makes
# safe: once-per-bucket compilation under concurrency, and engine-cache
# eviction that never touches an engine a live request holds.


def test_concurrent_first_hits_compile_bucket_once(rng):
    """N threads first-hitting the SAME bucket concurrently must produce ONE
    trace (the per-engine bucket lock), identical scores, and no duplicate
    trace work that would trip trace_count gates."""
    import threading

    eng, req = _guard_model_and_req(rng)
    request = req(50)
    expected_holder = {}
    results = [None] * 8
    errors = []
    barrier = threading.Barrier(8)

    def worker(i):
        try:
            barrier.wait(timeout=30)
            results[i] = eng.score(request)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errors
    assert eng.trace_count == 1  # one program, traced exactly once
    expected_holder["ref"] = results[0]
    for out in results:
        np.testing.assert_array_equal(out, expected_holder["ref"])
    # steady state afterwards: same bucket, still no retrace, lock-free path
    eng.score(req(60))
    assert eng.trace_count == 1


def test_concurrent_first_hits_on_different_buckets(rng):
    """Different buckets first-hit concurrently: each compiles exactly once
    (2 traces total), none serializes the other into a wrong count."""
    import threading

    eng, req = _guard_model_and_req(rng)
    reqs = {50: req(50), 100: req(100)}  # 64 and 128 buckets
    errors = []
    barrier = threading.Barrier(2)

    def worker(n):
        try:
            barrier.wait(timeout=30)
            eng.score(reqs[n])
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(n,)) for n in (50, 100)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errors
    assert eng.trace_count == 2


def test_eviction_mid_flight_never_breaks_a_held_engine(rng):
    """evict_engine/clear_engine_cache drop the cache ENTRY only: a thread
    scoring through an engine evicted mid-flight keeps getting bitwise-stable
    answers, and the next cache lookup builds a fresh engine."""
    import threading

    from photon_ml_tpu.serving import evict_engine

    model = GameModel(
        models={"fixed": fixed_model(rng), "per-user": random_model(rng, "userId", 10)}
    )
    eng = get_engine(model)
    data = glmix_input(rng, with_items=False)
    reference = eng.score(data)
    outputs = []
    errors = []
    started = threading.Event()

    def scorer():
        try:
            for _ in range(20):
                outputs.append(eng.score(data))
                started.set()
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    t = threading.Thread(target=scorer)
    t.start()
    assert started.wait(30)
    assert evict_engine(eng.fingerprint) == 1  # mid-flight eviction
    clear_engine_cache()  # and the bigger hammer, same contract
    t.join(60)
    assert not errors and len(outputs) == 20
    for out in outputs:
        np.testing.assert_array_equal(out, reference)
    # the evicted fingerprint is gone: a fresh lookup builds a new engine
    assert get_engine(model) is not eng
    # the held engine still works even after being fully superseded
    np.testing.assert_array_equal(eng.score(data), reference)


def test_evict_engine_is_fingerprint_scoped(rng):
    from photon_ml_tpu.serving import evict_engine

    m1 = GameModel(models={"fixed": fixed_model(rng)})
    m2 = GameModel(models={"fixed": fixed_model(rng)})
    e1, e2 = get_engine(m1), get_engine(m2)
    assert e1 is not e2
    assert evict_engine(e1.fingerprint) == 1
    assert get_engine(m2) is e2  # untouched entry survives
    assert get_engine(m1) is not e1
    assert evict_engine("not-a-fingerprint") == 0


# ------------------------------------------------------------------------
# GLM family matrix: the fused engine and the micro-batching frontend must
# serve EVERY family the trainer produces (logistic, linear, Poisson,
# smoothed hinge) — score parity bitwise vs eager, predict through the
# family's link function, frontend coalescing bitwise vs direct engine calls.
# ------------------------------------------------------------------------

from photon_ml_tpu.models.glm import model_class_for_task

ALL_TASKS = [
    TaskType.LOGISTIC_REGRESSION,
    TaskType.LINEAR_REGRESSION,
    TaskType.POISSON_REGRESSION,
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
]


def family_glmix_model(rng, task):
    """FE + per-user RE model pair of one family (the trainer's output shape
    for that task)."""
    task = TaskType(task)
    re = random_model(rng, "userId", n_entities=10)
    re = __import__("dataclasses").replace(re, task=task)
    return GameModel(
        models={
            "fixed": fixed_model(rng, cls=model_class_for_task(task)),
            "per-user": re,
        }
    )


@pytest.mark.parametrize("task", ALL_TASKS)
def test_family_matrix_engine_score_parity(rng, task):
    model = family_glmix_model(rng, task)
    assert_parity(model, glmix_input(rng, with_items=False))


@pytest.mark.parametrize("task", ALL_TASKS)
def test_family_matrix_predict_applies_the_link(rng, task):
    """predict = link^-1(score + offsets) per family. Default float64 offsets
    take the engine's host-side link branch (full precision, documented in
    engine.predict): the family's numpy link applied to the engine's own
    margins, compared at one-ulp tolerance — numpy's vectorized exp may
    differ from itself in the last bit depending on buffer alignment
    (SIMD body vs scalar tail), so exact equality would be flaky for the
    exp-bearing links. Margin-identity families compare bitwise."""
    from photon_ml_tpu.serving import get_engine

    model = family_glmix_model(rng, task)
    data = glmix_input(rng, with_items=False)
    eng = get_engine(model)
    margins = eng.score(data, include_offsets=True)
    task = TaskType(task)
    if task == TaskType.LOGISTIC_REGRESSION:
        expect = 1.0 / (1.0 + np.exp(-margins))
    elif task == TaskType.POISSON_REGRESSION:
        expect = np.exp(margins)
    else:  # linear and smoothed hinge predict the raw margin
        np.testing.assert_array_equal(eng.predict(data), margins)
        return
    np.testing.assert_allclose(eng.predict(data), expect, rtol=1e-15, atol=0)


@pytest.mark.parametrize("task", ALL_TASKS)
def test_family_matrix_device_link_predict(rng, task):
    """Device-representable (f32) offsets take the FUSED on-device link
    branch; it must agree with the host link to float tolerance (different
    fusion => not bitwise, the PR 1 lesson)."""
    from photon_ml_tpu.serving import get_engine

    model = family_glmix_model(rng, task)
    data = glmix_input(rng, with_items=False)
    data = __import__("dataclasses").replace(
        data, offsets=data.offsets.astype(np.float32)
    )
    eng = get_engine(model)
    margins = np.asarray(eng.score(data, include_offsets=True), dtype=np.float64)
    task = TaskType(task)
    if task == TaskType.LOGISTIC_REGRESSION:
        expect = 1.0 / (1.0 + np.exp(-margins))
    elif task == TaskType.POISSON_REGRESSION:
        expect = np.exp(margins)
    else:
        expect = margins
    np.testing.assert_allclose(eng.predict(data), expect, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("task", ALL_TASKS)
def test_family_matrix_frontend_coalescing_parity(rng, task):
    """Coalesced frontend responses must be bitwise what a direct engine call
    returns, for every family (the per-row independence contract does not
    care about the link/loss, but the dispatch plumbing must not either)."""
    from photon_ml_tpu.serving import FrontendConfig, ServingFrontend, get_engine

    model = family_glmix_model(rng, task)
    eng = get_engine(model)
    reqs = [glmix_input(rng, n=9, with_items=False) for _ in range(4)]
    frontend = ServingFrontend(eng, FrontendConfig(max_wait_ms=5.0, max_batch=8))
    try:
        futures = [frontend.submit(r) for r in reqs]
        for r, fut in zip(reqs, futures):
            out = fut.result(30)
            direct = eng.score(r)
            assert out.dtype == direct.dtype
            np.testing.assert_array_equal(out, direct)
    finally:
        frontend.close()


# --------------------------------------------------------------------------
# tenant isolation across the PROCESS boundary: the front router
# (serving/router.py) keeps per-tenant buckets and priority-class admission
# honest while a replica endpoint dies out from under it. Backends here are
# real HTTP servers over real sockets; abruptly closing one gives the router
# the same wire signal a SIGKILLed replica process does (connect refused) —
# the full process lifecycle is benchmarks/fleet_proc_bench.py's job.
# --------------------------------------------------------------------------


def test_tenant_isolation_survives_replica_death(tmp_path, rng):
    from photon_ml_tpu.serving import (
        FleetHTTPServer,
        FrontendConfig,
        FrontRouter,
        ModelRouter,
        Overloaded,
        QuotaExceeded,
        ReplicaSet,
        RouterConfig,
        TenantQuota,
    )

    from tests.test_fleet import build_fleet
    from tests.test_hotswap import make_req

    # two single-replica "processes" sharing one checkpoint store: separate
    # ModelRouters on separate sockets, bitwise-identical coefficients
    root, rs0 = build_fleet(tmp_path, rng, n_replicas=1)
    rs1 = ReplicaSet.from_checkpoint(
        root, 1, name="m", config=FrontendConfig(max_wait_ms=0.0)
    )
    backends, servers = [], []
    for rs in (rs0, rs1):
        mr = ModelRouter()
        mr.add_model("m", rs)
        backends.append(mr)
        servers.append(FleetHTTPServer(mr, port=0).start())
    front = FrontRouter(
        [(s.host, s.port) for s in servers],
        RouterConfig(
            evict_after_failures=1, readmit_after_successes=1, max_attempts=2,
            connect_timeout_s=0.5, read_timeout_s=30.0,
            backoff_base_s=0.0, backoff_cap_s=0.0,
            fleet_budget_per_replica=1,
        ),
        seed=13, start_probes=False,
    )
    # router admission is per-model, so the priority-ordering check serves
    # the SAME replica sets under a second backend model name ("m-batch")
    # registered at the router under the batch class
    for mr, rs in zip(backends, (rs0, rs1)):
        mr.add_model("m-batch", rs)
    front.register_model(
        "m", priority="interactive",
        tenant_quotas={"capped": TenantQuota(rate=0.0, burst=3.0)},
    )
    front.register_model("m-batch", priority="batch")
    req = make_req(rng)
    direct = rs0.replicas[0].engine.score(req)
    try:
        # healthy fleet: both classes admit, responses bitwise across 2 hops
        out, gen = front.score("m", req)
        assert gen == 1 and out.dtype == direct.dtype
        np.testing.assert_array_equal(out, direct)
        out, _ = front.score("m-batch", req)
        np.testing.assert_array_equal(out, direct)

        # kill one replica endpoint: connect refused, exactly what a
        # SIGKILLed replica process looks like from the router
        servers[1].close()

        # the capped tenant gets its full burst and NOT ONE request more —
        # admitted requests may retry onto the survivor internally, but the
        # bucket is taken once per request, never per attempt
        ok = quota_shed = 0
        for _ in range(6):
            try:
                out, _ = front.score("m", req, tenant="capped")
            except QuotaExceeded:
                quota_shed += 1
                continue
            np.testing.assert_array_equal(out, direct)
            ok += 1
        assert (ok, quota_shed) == (3, 3)
        # ... and its exhaustion starves nobody else
        out, _ = front.score("m", req, tenant="someone-else")
        np.testing.assert_array_equal(out, direct)

        # capacity halved -> the batch class sheds FIRST (typed), while the
        # interactive class keeps serving from the survivor
        assert len(front.rotation()) == 1  # passive accounting evicted it
        with pytest.raises(Overloaded):
            front.score("m-batch", req)
        out, _ = front.score("m", req, tenant="someone-else")
        np.testing.assert_array_equal(out, direct)

        kinds = {i.kind for i in front.incidents}
        assert {"replica-evict", "quota-shed", "overload"} <= kinds
        sheds = front.stats()["sheds_by_cause"]
        assert sheds["quota"] == 3 and sheds["overload"] >= 1
    finally:
        front.close()
        servers[0].close()
        for mr in backends:
            mr.close()
