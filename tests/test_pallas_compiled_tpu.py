"""COMPILED-path checks for the fused Pallas kernels on a real TPU.

The suite's conftest pins every in-process test to the simulated CPU platform,
where the kernels run in interpret mode — which is exactly how the round-2
code shipped a kernel that could not compile on hardware (Mosaic rejects
scalar stores into VMEM refs; interpret mode permits them). These tests
close that gap: they spawn a subprocess WITHOUT the CPU pin and run the
kernels through the real Mosaic compiler, asserting numerical agreement
with the f64 ground truth (benchmarks/pallas_microbench.py's parity gate).

Skipped (not failed) when no TPU answers the bounded probe — the tunnel is
intermittent — and when another process holds the serial-measurement lock
(/tmp/tpu_busy, see benchmarks/tpu_session.sh): probing mid-measurement
would perturb banked timings.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TPU_BUSY_LOCK = "/tmp/tpu_busy"


def _clean_env():
    """The ambient (non-conftest) environment: drop the CPU pin the test
    harness exports so the child sees the real default backend."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    flags = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = " ".join(
        tok
        for tok in flags.split()
        if "xla_force_host_platform_device_count" not in tok
    )
    return env


def _tpu_available() -> bool:
    if os.path.exists(TPU_BUSY_LOCK):
        return False
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.devices()[0].platform)"],
            capture_output=True,
            text=True,
            timeout=120,
            env=_clean_env(),
        )
    except subprocess.TimeoutExpired:
        return False
    return proc.returncode == 0 and proc.stdout.strip() == "tpu"


@pytest.mark.skipif(
    os.environ.get("PHOTON_TPU_TESTS", "") in ("", "0"),
    reason="opt-in (PHOTON_TPU_TESTS=1): needs the real TPU tunnel",
)
def test_fused_kernels_compile_and_agree_on_tpu():
    if not _tpu_available():
        pytest.skip("no healthy TPU tunnel (or /tmp/tpu_busy held)")
    # hold the serial-measurement lock for the run's duration: a measurement
    # session starting between the probe and the subprocess would otherwise
    # share the chip with this test, perturbing both. O_EXCL, so a lock that
    # appeared since the probe is never clobbered (and never deleted below).
    try:
        os.close(os.open(TPU_BUSY_LOCK, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
    except OSError:
        # FileExistsError for a lost race against another O_EXCL holder;
        # IsADirectoryError when a session script took the lock via mkdir
        # (benchmarks/tpu_session*.sh) between the probe and here.
        pytest.skip("another process acquired /tmp/tpu_busy during the probe")
    try:
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "benchmarks", "pallas_microbench.py"),
                "--shapes",
                "20000x64,8192x512",
                "--repeats",
                "3",
            ],
            capture_output=True,
            text=True,
            timeout=900,
            env=_clean_env(),
            cwd=REPO,
        )
    finally:
        try:
            os.remove(TPU_BUSY_LOCK)
        except OSError:
            pass
    assert proc.returncode == 0, f"microbench failed:\n{proc.stderr[-2000:]}"
    records = [
        json.loads(line)
        for line in proc.stdout.strip().splitlines()
        if line.startswith("{")
    ]
    kernels = {(r["kernel"], r["shape"]) for r in records if "kernel" in r}
    # both kernels compiled + passed the f64 parity gate at both shapes
    assert ("value_grad", "20000x64") in kernels
    assert ("hvp", "8192x512") in kernels
    for r in records:
        assert r["backend"] == "tpu"
