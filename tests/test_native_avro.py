"""Native Avro decoder tests: result parity with the pure-Python path over every
supported field shape, fallback behavior, and an ingest speedup smoke check."""

import time

import numpy as np
import pytest

from photon_ml_tpu.data import avro_io, native_avro
from photon_ml_tpu.data.readers import read_merged_avro
from photon_ml_tpu.estimators.config import FeatureShardConfiguration

pytestmark = pytest.mark.skipif(
    not native_avro.available(), reason="native decoder unavailable (no g++)"
)


def write_fixture(path, rng, n=400, d=6, with_nulls=True):
    def records():
        for i in range(n):
            yield {
                "uid": None if (with_nulls and i % 7 == 0) else f"s{i}",
                "label": float(i % 2),
                "features": [
                    {"name": f"f{j}", "term": f"t{j % 2}", "value": float(rng.normal())}
                    for j in range(int(rng.integers(0, d)))
                ],
                "metadataMap": {"userId": f"u{i % 5}", "extra": "x"},
                "weight": None if (with_nulls and i % 5 == 0) else 2.0,
                "offset": None if (with_nulls and i % 3 == 0) else 0.25,
            }

    avro_io.write_container(path, avro_io.TRAINING_EXAMPLE_SCHEMA, records())


SHARDS = {"shardA": FeatureShardConfiguration(feature_bags=("features",))}


class TestNativeParity:
    def test_matches_python_path(self, tmp_path, rng):
        path = str(tmp_path / "data.avro")
        write_fixture(path, rng)
        nat, nat_maps, nat_uids = read_merged_avro(path, SHARDS, id_tags=["userId"])
        py, py_maps, py_uids = read_merged_avro(
            path, SHARDS, id_tags=["userId"], use_native=False
        )
        assert nat_maps["shardA"].size == py_maps["shardA"].size
        np.testing.assert_array_equal(np.asarray(nat.labels), np.asarray(py.labels))
        np.testing.assert_array_equal(nat.offsets, py.offsets)
        np.testing.assert_array_equal(nat.weights, py.weights)
        np.testing.assert_array_equal(
            nat.id_columns["userId"], py.id_columns["userId"]
        )
        np.testing.assert_allclose(
            nat.features["shardA"].toarray(), py.features["shardA"].toarray()
        )
        # null uids default to the row ordinal on both paths
        assert list(nat_uids) == list(py_uids)

    def test_existing_index_map_respected(self, tmp_path, rng):
        path = str(tmp_path / "data.avro")
        write_fixture(path, rng)
        _, maps, _ = read_merged_avro(path, SHARDS)
        nat, _, _ = read_merged_avro(path, SHARDS, index_maps=maps)
        py, _, _ = read_merged_avro(path, SHARDS, index_maps=maps, use_native=False)
        np.testing.assert_allclose(
            nat.features["shardA"].toarray(), py.features["shardA"].toarray()
        )

    def test_unlabeled_schema_parity(self, tmp_path):
        """ResponsePredictionAvro-shaped records (response field name)."""
        schema = {
            "name": "SimplifiedResponsePrediction",
            "type": "record",
            "fields": [
                {"name": "response", "type": "double"},
                {"name": "features", "type": {"type": "array",
                                              "items": avro_io.FEATURE_SCHEMA}},
            ],
        }
        path = str(tmp_path / "r.avro")
        avro_io.write_container(path, schema, [
            {"response": 1.0, "features": [{"name": "a", "term": "", "value": 3.0}]},
            {"response": 0.0, "features": []},
        ])
        nat, _, _ = read_merged_avro(path, SHARDS)
        py, _, _ = read_merged_avro(path, SHARDS, use_native=False)
        np.testing.assert_array_equal(np.asarray(nat.labels), np.asarray(py.labels))
        np.testing.assert_allclose(
            nat.features["shardA"].toarray(), py.features["shardA"].toarray()
        )

    def test_unsupported_schema_falls_back(self, tmp_path):
        """A schema with an int field is outside the native set; read_merged_avro
        must still work via the Python path."""
        schema = {
            "name": "Weird",
            "type": "record",
            "fields": [
                {"name": "label", "type": "double"},
                {"name": "features", "type": {"type": "array",
                                              "items": avro_io.FEATURE_SCHEMA}},
                {"name": "count", "type": "long"},
            ],
        }
        path = str(tmp_path / "w.avro")
        avro_io.write_container(path, schema, [
            {"label": 1.0, "features": [], "count": 3},
        ])
        assert native_avro.field_types_for_schema(schema["fields"]) is None
        data, _, _ = read_merged_avro(path, SHARDS)
        assert data.n == 1


class TestDecoderPrimitives:
    def test_decode_block_roundtrip(self):
        import io as _io

        buf = _io.BytesIO()
        schema = avro_io.Schema(avro_io.TRAINING_EXAMPLE_SCHEMA)
        recs = [
            {
                "uid": "u1", "label": 2.5,
                "features": [{"name": "n", "term": "t", "value": 7.0}],
                "metadataMap": {"k": "v"}, "weight": 3.0, "offset": None,
            }
        ]
        for r in recs:
            avro_io.encode(buf, schema.root, r)
        ftypes = native_avro.field_types_for_schema(
            avro_io.TRAINING_EXAMPLE_SCHEMA["fields"]
        )
        with native_avro.decode_block(buf.getvalue(), 1, ftypes) as block:
            assert block.doubles(1)[0] == 2.5
            assert np.isnan(block.doubles(5)[0])  # null offset -> NaN
            assert block.doubles(4)[0] == 3.0
            rows, no, nl, to, tl, vals = block.features(2)
            assert vals[0] == 7.0
            assert block.string_at(no[0], nl[0]) == "n"
            assert block.string_at(to[0], tl[0]) == "t"
            r_, ko, kl, vo, vl = block.map_entries(3)
            assert block.string_at(ko[0], kl[0]) == "k"
            assert block.string_at(vo[0], vl[0]) == "v"

    def test_malformed_block_raises(self):
        ftypes = [native_avro.F_DOUBLE]
        with pytest.raises(ValueError, match="malformed|trailing"):
            native_avro.decode_block(b"\x01\x02", 1, ftypes)

    def test_trailing_bytes_raises(self):
        payload = np.float64(1.0).tobytes() + b"extra"
        with pytest.raises(ValueError, match="trailing"):
            native_avro.decode_block(payload, 1, [native_avro.F_DOUBLE])


def test_native_ingest_speedup(tmp_path, rng):
    """The native path should beat pure Python comfortably on a larger file."""
    path = str(tmp_path / "big.avro")
    write_fixture(path, rng, n=8000, d=12, with_nulls=False)
    t0 = time.perf_counter()
    read_merged_avro(path, SHARDS)
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    read_merged_avro(path, SHARDS, use_native=False)
    t_python = time.perf_counter() - t0
    print(f"native={t_native:.3f}s python={t_python:.3f}s speedup={t_python/t_native:.1f}x")
    assert t_native < t_python


class TestReviewRegressions:
    def test_non_nullable_weight_offset_parity(self, tmp_path):
        """ResponsePredictionAvro declares weight/offset as plain doubles; the
        native path must read them, not silently default to 1/0."""
        path = str(tmp_path / "rp.avro")
        avro_io.write_container(path, avro_io.RESPONSE_PREDICTION_SCHEMA, [
            {"uid": "a", "response": 1.0, "offset": 0.25, "weight": 2.0,
             "features": [{"name": "x", "term": "", "value": 1.0}]},
            {"uid": "b", "response": 0.0, "offset": -0.5, "weight": 3.0,
             "features": []},
        ])
        nat, _, _ = read_merged_avro(path, SHARDS)
        py, _, _ = read_merged_avro(path, SHARDS, use_native=False)
        np.testing.assert_array_equal(nat.weights, py.weights)
        np.testing.assert_array_equal(nat.offsets, py.offsets)
        np.testing.assert_array_equal(nat.weights, [2.0, 3.0])
        np.testing.assert_array_equal(nat.offsets, [0.25, -0.5])

    def test_null_labels_parity(self, tmp_path):
        """Nullable labels: nulls default to 0.0 (never NaN), and an all-null
        label column means has_labels is False — matching the Python path."""
        schema = {
            "name": "NullableLabel",
            "type": "record",
            "fields": [
                {"name": "label", "type": ["null", "double"], "default": None},
                {"name": "features", "type": {"type": "array",
                                              "items": avro_io.FEATURE_SCHEMA}},
            ],
        }
        path = str(tmp_path / "nl.avro")
        avro_io.write_container(path, schema, [
            {"label": None, "features": []},
            {"label": 1.0, "features": []},
        ])
        nat, _, _ = read_merged_avro(path, SHARDS)
        py, _, _ = read_merged_avro(path, SHARDS, use_native=False)
        assert nat.has_labels and py.has_labels
        np.testing.assert_array_equal(np.asarray(nat.labels), np.asarray(py.labels))
        assert not np.any(np.isnan(np.asarray(nat.labels)))

        path2 = str(tmp_path / "allnull.avro")
        avro_io.write_container(path2, schema, [
            {"label": None, "features": []},
            {"label": None, "features": []},
        ])
        nat2, _, _ = read_merged_avro(path2, SHARDS)
        py2, _, _ = read_merged_avro(path2, SHARDS, use_native=False)
        assert nat2.has_labels == py2.has_labels == False  # noqa: E712

    def test_empty_uid_parity(self, tmp_path):
        """Empty-string uids fall back to a FILE-anchored synthetic uid
        (<part-file>#<row-in-file>) on BOTH paths — positional ordinals would
        depend on which slice of the part files a reader saw and collide
        across the processes of a multi-process scoring run."""
        path = str(tmp_path / "uid.avro")
        avro_io.write_container(path, avro_io.TRAINING_EXAMPLE_SCHEMA, [
            {"uid": "", "label": 1.0, "features": [], "metadataMap": {},
             "weight": 1.0, "offset": 0.0},
            {"uid": "real", "label": 0.0, "features": [], "metadataMap": {},
             "weight": 1.0, "offset": 0.0},
        ])
        _, _, nat_uids = read_merged_avro(path, SHARDS)
        _, _, py_uids = read_merged_avro(path, SHARDS, use_native=False)
        assert list(nat_uids) == list(py_uids) == ["uid.avro#0", "real"]

    def test_comma_separated_multi_path(self, tmp_path, rng):
        """--input-data-directories is comma-separated (reference
        inputDataDirectories contract); part files concatenate across paths."""
        d1, d2 = tmp_path / "day1", tmp_path / "day2"
        d1.mkdir(), d2.mkdir()
        write_fixture(str(d1 / "part-0.avro"), rng, n=30, with_nulls=False)
        write_fixture(str(d2 / "part-0.avro"), rng, n=20, with_nulls=False)
        joined = f"{d1},{d2}"
        nat, _, _ = read_merged_avro(joined, SHARDS)
        py, _, _ = read_merged_avro(joined, SHARDS, use_native=False)
        assert nat.n == py.n == 50
        np.testing.assert_allclose(
            nat.features["shardA"].toarray(), py.features["shardA"].toarray()
        )
        as_list, _, _ = read_merged_avro([str(d1), str(d2)], SHARDS)
        assert as_list.n == 50

    def test_corrupt_cached_so_rebuilds(self, tmp_path, monkeypatch):
        """A corrupt/incompatible cached .so must not crash the default
        use_native path: _load drops it and rebuilds from source."""
        import shutil

        cache = tmp_path / "build"
        cache.mkdir()
        bad = cache / "libphoton_avro.so"
        bad.write_bytes(b"not an elf file")
        src = native_avro._SOURCE
        monkeypatch.setattr(native_avro, "_CACHE_DIR", str(cache))
        monkeypatch.setattr(native_avro, "_lib", None)
        monkeypatch.setattr(native_avro, "_lib_error", None)
        # make the bad artifact look fresher than the source (committed files
        # lose their mtimes on checkout)
        import os as _os
        st = _os.stat(src)
        _os.utime(bad, (st.st_atime + 10, st.st_mtime + 10))
        try:
            assert native_avro.available()
        finally:
            monkeypatch.undo()
            shutil.rmtree(cache, ignore_errors=True)


class TestNativeScoreEncoder:
    def _write_both(self, tmp_path, n=500, with_labels=True, with_uids=True):
        import types

        from photon_ml_tpu.cli.game_scoring_driver import _write_scores

        rng = np.random.default_rng(12)
        scores = rng.normal(size=n)
        data = types.SimpleNamespace(
            has_labels=with_labels,
            labels=rng.random(n) if with_labels else None,
            weights=np.abs(rng.normal(size=n)) + 0.1,
        )
        uids = [f"uid-{i}" for i in range(n)] if with_uids else None
        p_native = str(tmp_path / "native.avro")
        p_python = str(tmp_path / "python.avro")
        _write_scores(p_native, uids, scores, data, "m1", use_native=True)
        _write_scores(p_python, uids, scores, data, "m1", use_native=False)
        return p_native, p_python

    @pytest.mark.parametrize("with_labels", [True, False])
    def test_native_matches_python_encoder(self, tmp_path, with_labels):
        from photon_ml_tpu.data import native_avro

        if not native_avro.available():
            pytest.skip("native library unavailable")
        p_native, p_python = self._write_both(tmp_path, with_labels=with_labels)
        a = list(avro_io.read_container(p_native))
        b = list(avro_io.read_container(p_python))
        assert a == b
        assert len(a) == 500
        assert a[3]["uid"] == "uid-3" and a[3]["modelId"] == "m1"
        # identical bytes while both paths fit one block (n <= 4096, the
        # Python writer's block size; larger outputs differ only in block
        # boundaries)
        assert open(p_native, "rb").read() == open(p_python, "rb").read()

    def test_multi_block_split(self, tmp_path):
        import types

        from photon_ml_tpu.cli.game_scoring_driver import _write_scores
        from photon_ml_tpu.data import native_avro

        if not native_avro.available():
            pytest.skip("native library unavailable")
        n = 70000  # > one 65536-record block
        scores = np.arange(n, dtype=np.float64)
        data = types.SimpleNamespace(
            has_labels=False, labels=None, weights=np.ones(n)
        )
        path = str(tmp_path / "big.avro")
        _write_scores(path, None, scores, data, "", use_native=True)
        recs = list(avro_io.read_container(path))
        assert len(recs) == n
        assert recs[-1]["predictionScore"] == float(n - 1)
        assert recs[12345]["uid"] == "12345"
