"""jaxlint analyzer tests: fixture corpus, suppressions, baseline, CLI.

The fixture files under tests/fixtures/jaxlint/ carry ``# EXPECT: RULE``
markers on every line that must yield exactly one finding of that rule;
every unmarked line must yield nothing. That makes each fixture a complete
positive AND negative spec — a new false positive in the analyzer fails
these tests even if it appears on a line nobody thought about.

The analyzer is pure stdlib: these tests import it through the package but
never need a jax runtime.
"""

import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

from photon_ml_tpu.analysis import baseline as baseline_mod
from photon_ml_tpu.analysis import linter
from photon_ml_tpu.analysis.rules import RuleConfig, RULES, Severity

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "fixtures" / "jaxlint"

_EXPECT_RE = re.compile(r"#\s*EXPECT:\s*([A-Z]{2}\d{3})")


def expected_findings(path: Path) -> list:
    out = []
    for lineno, line in enumerate(path.read_text().splitlines(), start=1):
        for rule in _EXPECT_RE.findall(line):
            out.append((lineno, rule))
    return sorted(out)


def actual_findings(path: Path, config=None) -> linter.LintResult:
    return linter.lint_source(path.read_text(), path.name, config)


@pytest.mark.parametrize(
    "fixture",
    ["hs001.py", "rt001.py", "tr001.py", "pr001.py", "dn001.py", "np001.py",
     "mp001.py", "cc001.py", "cc002.py", "cc003.py", "cc004.py", "clean.py"],
)
def test_fixture_findings_match_expectations(fixture):
    path = FIXTURES / fixture
    result = actual_findings(path)
    got = sorted((f.line, f.rule) for f in result.findings)
    assert got == expected_findings(path), (
        f"{fixture}: findings diverge from # EXPECT markers.\n"
        f"got:      {got}\nexpected: {expected_findings(path)}\n"
        + "\n".join(f.format_human() for f in result.findings)
    )


def test_clean_fixture_is_fully_clean():
    result = actual_findings(FIXTURES / "clean.py")
    assert result.findings == [] and result.suppressed == []


def test_every_rule_has_fixture_coverage():
    """Each non-meta rule must be exercised by at least one positive case."""
    covered = set()
    for f in FIXTURES.glob("*.py"):
        covered.update(rule for _, rule in expected_findings(f))
    assert covered >= (set(RULES) - {"SUP001"})


# ---------------------------------------------- whole-program context (v2)


def test_crosstaint_package_v1_silent_v2_exact():
    """The regression the project context exists for: the two-module
    tracker-sync shape (the PR 2 per-iteration host pull) is INVISIBLE to
    module-local analysis — v1 must report nothing for the package — and
    the whole-program scan must report exactly the EXPECT markers."""
    pkg = FIXTURES / "crosstaint_pkg"
    v1 = linter.lint_paths([pkg], rel_root=str(REPO), project=False)
    assert v1.findings == [], (
        "module-local scan is no longer blind to the cross-module fixture "
        "(the fixture stopped pinning the v1 gap):\n"
        + "\n".join(f.format_human() for f in v1.findings)
    )
    v2 = linter.lint_paths([pkg], rel_root=str(REPO), project=True)
    got = sorted((Path(f.path).name, f.line, f.rule) for f in v2.findings)
    expected = sorted(
        (p.name, line, rule)
        for p in pkg.glob("*.py")
        for line, rule in expected_findings(p)
    )
    assert got == expected, (
        "whole-program findings diverge from # EXPECT markers.\n"
        f"got:      {got}\nexpected: {expected}\n"
        + "\n".join(f.format_human() for f in v2.findings)
    )
    # the jit-reachable sync sink is an ERROR (it raises under trace), the
    # descent-loop per-iteration sync stays a warning
    sev = {(Path(f.path).name, f.line): f.severity for f in v2.findings}
    assert sev[("tracker.py", 27)] == Severity.ERROR
    assert sev[("loop.py", 29)] == Severity.WARNING


def test_parallel_scan_matches_serial():
    """--jobs is a pure fan-out: same findings, same scanned set, in the
    same order, whatever the worker count."""
    paths = [REPO / "photon_ml_tpu" / "analysis"]
    serial = linter.lint_paths(paths, rel_root=str(REPO))
    par = linter.lint_paths(paths, rel_root=str(REPO), jobs=2)
    def key(findings):
        return [(f.path, f.line, f.col, f.rule, f.message) for f in findings]

    assert key(par.findings) == key(serial.findings)
    assert key(par.suppressed) == key(serial.suppressed)
    assert par.scanned == serial.scanned


# ---------------------------------------------------------------- suppression


def test_suppression_with_reason_silences_finding():
    result = actual_findings(FIXTURES / "suppressed.py")
    by_func_line = {(f.line, f.rule) for f in result.findings}
    sup = {(f.line, f.rule) for f in result.suppressed}
    src = (FIXTURES / "suppressed.py").read_text().splitlines()

    def line_of(snippet):
        return next(i for i, l in enumerate(src, start=1) if snippet in l)

    # reasoned suppressions: finding moves to .suppressed
    assert (line_of("per-item scores leave the device"), "HS001") in sup
    assert (line_of("intentional host mirror"), "HS001") in sup
    # reasonless suppression: SUP001 AND the original finding stay active
    bad = next(i for i, l in enumerate(src, start=1)
               if l.rstrip().endswith("disable=HS001"))
    assert (bad, "SUP001") in by_func_line and (bad, "HS001") in by_func_line
    # unknown rule id: SUP001; the known id still suppresses (reason present)
    unk = line_of("ZZ999")
    assert (unk, "SUP001") in by_func_line
    assert (unk, "HS001") in sup
    # suppressing the wrong rule leaves the real finding active
    wrong = line_of("suppressing the wrong rule")
    assert (wrong, "HS001") in by_func_line


def test_multi_rule_suppression_with_space_after_comma():
    """'disable=HS001, RT001 <reason>' must suppress BOTH rules — a lazy ids
    parse would treat 'RT001 <reason>' as the reason and silently narrow the
    suppression to HS001."""
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "f = jax.jit(lambda a, cfg: a)\n"
        "def g(xs):\n"
        "    for x in xs:\n"
        "        v = float(jnp.sum(x)); f(x, {'k': 1})  # jaxlint: disable=HS001, RT001 both intended here\n"
        "    return v\n"
    )
    result = linter.lint_source(src, "t.py")
    assert result.findings == [], [f.format_human() for f in result.findings]
    assert {f.rule for f in result.suppressed} == {"HS001", "RT001"}


def test_npview_arithmetic_result_is_writable():
    """v = np.asarray(<jax>) is a read-only view, but v * 2.0 allocates a
    fresh writable array — mutating THAT must not fire NP001."""
    src = (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def f(xs):\n"
        "    v = np.asarray(jnp.sum(xs))\n"
        "    w = v * 2.0\n"
        "    w[0] = 1.0\n"
        "    v[0] = 1.0\n"  # the view itself: still NP001
        "    return w\n"
    )
    result = linter.lint_source(src, "t.py")
    assert [(f.line, f.rule) for f in result.findings] == [(7, "NP001")]


def test_unparseable_file_is_an_error_not_a_pass(tmp_path):
    """A file the analyzer cannot parse must surface as an error, stay out of
    the scanned set (no bogus staleness), and fail the CLI."""
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")
    result = linter.lint_paths([bad], rel_root=str(tmp_path))
    assert result.errors and not result.findings
    assert "bad.py" not in result.scanned
    r = _run_cli(str(bad), "--no-baseline")
    assert r.returncode == 3, (r.returncode, r.stdout, r.stderr)


def test_scan_root_under_hidden_ancestor_still_scans(tmp_path):
    """Skip-dir filtering applies below the scan root only: a checkout under
    a hidden/'build'-named ancestor must not silently scan as empty."""
    root = tmp_path / ".cache" / "build" / "pkg"
    root.mkdir(parents=True)
    (root / "mod.py").write_text(_LOOP_SYNC)
    (root / "__pycache__").mkdir()
    (root / "__pycache__" / "junk.py").write_text(_LOOP_SYNC)
    result = linter.lint_paths([root], rel_root=str(tmp_path))
    assert {f.rule for f in result.findings} == {"HS001"}
    assert all("__pycache__" not in p for p in result.scanned)


def test_sup001_cannot_be_suppressed():
    src = (
        "import jax.numpy as jnp\n"
        "def f(xs):\n"
        "    for x in xs:\n"
        "        v = float(jnp.sum(x))  # jaxlint: disable=HS001,SUP001\n"
        "    return v\n"
    )
    result = linter.lint_source(src, "t.py")
    assert {f.rule for f in result.findings} == {"SUP001", "HS001"}


# ---------------------------------------------------------------- rule config


def test_disable_rule():
    path = FIXTURES / "hs001.py"
    result = actual_findings(path, RuleConfig(disabled=frozenset({"HS001"})))
    assert result.findings == []


def test_severity_override():
    path = FIXTURES / "np001.py"
    result = actual_findings(
        path, RuleConfig(severity_overrides={"NP001": Severity.WARNING})
    )
    assert result.findings and all(f.severity == Severity.WARNING for f in result.findings)


def test_unknown_rule_config_rejected():
    with pytest.raises(ValueError):
        RuleConfig(disabled=frozenset({"XX123"}))


# ------------------------------------------------------------------- baseline


def _findings_for(src: str):
    return linter.lint_source(src, "mod.py").findings


_LOOP_SYNC = (
    "import jax.numpy as jnp\n"
    "def f(xs):\n"
    "    for x in xs:\n"
    "        v = float(jnp.sum(x))\n"
    "    return v\n"
)


def test_baseline_accepts_existing_and_catches_new():
    old = _findings_for(_LOOP_SYNC)
    counts = baseline_mod.to_counts(old)
    # same findings: clean
    d = baseline_mod.diff(old, counts)
    assert d.clean
    # a second, new sync appears: only IT is new
    new_src = _LOOP_SYNC.replace(
        "    return v\n", "        w = jnp.sum(x).item()\n    return v\n"
    )
    d = baseline_mod.diff(_findings_for(new_src), counts)
    assert len(d.new) == 1 and d.new[0].line_text == "w = jnp.sum(x).item()"
    assert not d.stale


def test_baseline_keys_survive_line_drift():
    old = _findings_for(_LOOP_SYNC)
    counts = baseline_mod.to_counts(old)
    shifted = "import os\n# a new comment line\n" + _LOOP_SYNC
    d = baseline_mod.diff(_findings_for(shifted), counts)
    assert d.clean, "an unrelated inserted line must not break the baseline"


def test_baseline_stale_entry_detected_and_scoped():
    old = _findings_for(_LOOP_SYNC)
    counts = baseline_mod.to_counts(old)
    fixed = _LOOP_SYNC.replace("float(jnp.sum(x))", "jnp.sum(x)")
    d = baseline_mod.diff(_findings_for(fixed), counts, scanned_paths={"mod.py"})
    assert d.stale and not d.new
    # same fix, but mod.py wasn't part of this scan: not stale
    d = baseline_mod.diff(_findings_for(fixed), counts, scanned_paths={"other.py"})
    assert not d.stale


def test_baseline_roundtrip(tmp_path):
    old = _findings_for(_LOOP_SYNC)
    p = tmp_path / "baseline.json"
    baseline_mod.save(str(p), old)
    assert baseline_mod.load(str(p)) == baseline_mod.to_counts(old)


def test_baseline_narrow_regenerate_preserves_unscanned_entries(tmp_path):
    """--update-baseline from a scan of one directory must not drop (and
    thereby re-arm as 'new') accepted findings in files that scan never
    visited — save() mirrors diff()'s scanned-path scoping."""
    p = tmp_path / "baseline.json"
    old = _findings_for(_LOOP_SYNC)  # path: mod.py
    baseline_mod.save(str(p), old)
    # regenerate from a scan that covered only other.py and found nothing
    baseline_mod.save(str(p), [], scanned_paths={"other.py"})
    assert baseline_mod.load(str(p)) == baseline_mod.to_counts(old)
    # a scan that DID cover mod.py and found nothing drops the entry
    baseline_mod.save(str(p), [], scanned_paths={"mod.py"})
    assert baseline_mod.load(str(p)) == {}


def test_committed_baseline_matches_fresh_scan():
    """The repo invariant CI enforces: a fresh scan of everything the lint
    job covers is exactly the committed baseline — nothing new, nothing
    stale."""
    result = linter.lint_paths(
        [REPO / "photon_ml_tpu", REPO / "benchmarks", REPO / "tests",
         REPO / "bench.py", REPO / "tools"],
        rel_root=str(REPO),
        exclude=["tests/fixtures/jaxlint"],
    )
    counts = baseline_mod.load(str(REPO / "tools" / "jaxlint_baseline.json"))
    d = baseline_mod.diff(result.findings, counts, scanned_paths=result.scanned)
    assert not d.new, "new jaxlint findings (fix or suppress with a reason):\n" + "\n".join(
        f.format_human() for f in d.new
    )
    assert not d.stale, (
        "stale baseline entries (a finding was fixed — regenerate with "
        "`python tools/jaxlint.py photon_ml_tpu benchmarks tests bench.py tools "
        "--update-baseline` and commit the smaller file):\n"
        + "\n".join(e["key"] for e in d.stale)
    )


# ------------------------------------------------------------------------ CLI


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, str(REPO / "tools" / "jaxlint.py"), *args],
        capture_output=True, text=True, cwd=str(REPO),
    )


def test_cli_package_scan_clean_against_baseline():
    r = _run_cli("photon_ml_tpu", "--format", "json")
    assert r.returncode == 0, r.stdout + r.stderr
    payload = json.loads(r.stdout)
    assert payload["summary"]["new"] == 0 and payload["summary"]["stale"] == 0


def test_cli_detects_seeded_violation(tmp_path):
    scratch = tmp_path / "seeded.py"
    scratch.write_text(
        "import jax\nimport jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x > 0:\n"
        "        return float(x)\n"
        "    return x\n"
    )
    r = _run_cli(str(scratch))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "TR001" in r.stdout and "HS001" in r.stdout


def test_cli_github_format_annotations(tmp_path):
    scratch = tmp_path / "seeded.py"
    scratch.write_text(
        "import jax\nimport jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x > 0:\n"
        "        return float(x)\n"
        "    return x\n"
    )
    r = _run_cli(str(scratch), "--no-baseline", "--format", "github")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "::error file=" in r.stdout and "title=jaxlint HS001" in r.stdout
    assert "title=jaxlint TR001" in r.stdout
    # workflow-command data must escape %/newlines; none of ours carry them,
    # but the annotation lines themselves must be single-line
    for line in r.stdout.splitlines():
        if line.startswith("::"):
            assert ",line=" in line and "::" in line[2:]


def test_cli_no_project_restores_v1(tmp_path):
    """The escape hatch: --no-project must scan the cross-module fixture
    silent (v1 semantics), while the default whole-program scan flags it."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    for f in (FIXTURES / "crosstaint_pkg").glob("*.py"):
        (pkg / f.name).write_text(f.read_text())
    v2 = _run_cli(str(pkg), "--no-baseline")
    assert v2.returncode == 1, v2.stdout + v2.stderr
    assert "HS001" in v2.stdout
    v1 = _run_cli(str(pkg), "--no-baseline", "--no-project")
    assert v1.returncode == 0, v1.stdout + v1.stderr


@pytest.mark.slow
def test_cli_parallel_jobs_same_output():
    """--jobs N produces byte-identical json findings to the serial scan.
    Slow-marked: two subprocess scans + a process pool on a small CI box;
    test_parallel_scan_matches_serial pins the same parity in-process."""
    serial = _run_cli("photon_ml_tpu/analysis", "--no-baseline", "--format", "json")
    par = _run_cli("photon_ml_tpu/analysis", "--no-baseline", "--format",
                   "json", "--jobs", "4")
    assert serial.returncode == par.returncode
    a, b = json.loads(serial.stdout), json.loads(par.stdout)
    assert a["findings"] == b["findings"]
    assert a["summary"] == b["summary"]


def test_cli_list_rules():
    r = _run_cli("--list-rules")
    assert r.returncode == 0
    for rule_id in RULES:
        assert rule_id in r.stdout
