"""Feature-axis model parallelism (parallel/feature_sharded.py): a 2-D
("data", "model") mesh shards the dense fixed-effect design matrix over both
axes and every [D]-vector (coefficients, optimizer state) over "model" — the
TPU-native replacement for the reference's PalDB off-heap index scale story
(PalDBIndexMap.scala:43-278: feature spaces too large for one machine)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.dataset import LabeledData
from photon_ml_tpu.normalization import NO_NORMALIZATION
from photon_ml_tpu.optimization.common import OptimizerConfig
from photon_ml_tpu.optimization.config import (
    GLMOptimizationConfiguration,
    RegularizationContext,
)
from photon_ml_tpu.optimization.solver_cache import glm_solver
from photon_ml_tpu.parallel import (
    make_mesh2,
    shard_labeled_data_2d,
    train_glm_feature_sharded,
)
from photon_ml_tpu.parallel.feature_sharded import MODEL_AXIS, feature_sharding
from photon_ml_tpu.types import (
    OptimizerType,
    RegularizationType,
    TaskType,
    VarianceComputationType,
)


def _cfg(opt=OptimizerType.LBFGS):
    return GLMOptimizationConfiguration(
        optimizer_config=OptimizerConfig(
            optimizer_type=opt, max_iterations=80, tolerance=1e-10
        ),
        regularization_context=RegularizationContext(RegularizationType.L2),
        regularization_weight=1.0,
    )


def _problem(rng, n=600, d=37):
    X = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ w)))).astype(float)
    return X, y


def _single_device_reference(X, y, cfg, task=TaskType.LOGISTIC_REGRESSION):
    data = LabeledData.build(X, y, dtype=jnp.float64)
    solve = glm_solver(
        task, cfg.optimizer_config, False, False, False, VarianceComputationType.NONE
    )
    d = X.shape[1]
    res, _ = solve(
        data,
        jnp.zeros(d, dtype=jnp.float64),
        jnp.asarray(cfg.l2_weight, dtype=jnp.float64),
        jnp.asarray(0.0, dtype=jnp.float64),
        jnp.zeros((0,), dtype=jnp.float64),
        jnp.zeros((0,), dtype=jnp.float64),
        NO_NORMALIZATION,
    )
    return np.asarray(res.coefficients)


@pytest.mark.parametrize("shape", [(2, 4), (4, 2), (1, 8)])
def test_matches_single_device(rng, eight_devices, shape):
    X, y = _problem(rng)
    cfg = _cfg()
    mesh = make_mesh2(*shape)
    sharded, n0, d0 = shard_labeled_data_2d(
        LabeledData.build(X, y, dtype=jnp.float64), mesh
    )
    res, _ = train_glm_feature_sharded(sharded, TaskType.LOGISTIC_REGRESSION, cfg, mesh)
    w2d = np.asarray(res.coefficients)
    ref = _single_device_reference(X, y, cfg)
    np.testing.assert_allclose(w2d[: X.shape[1]], ref, atol=1e-8)
    # padded (all-zero) feature columns see only the L2 term -> exactly 0
    assert np.all(w2d[X.shape[1] :] == 0.0)


def test_tron_hvp_path(rng, eight_devices):
    X, y = _problem(rng, n=500, d=20)
    cfg = _cfg(OptimizerType.TRON)
    mesh = make_mesh2(2, 4)
    sharded, _, _ = shard_labeled_data_2d(
        LabeledData.build(X, y, dtype=jnp.float64), mesh
    )
    res, _ = train_glm_feature_sharded(sharded, TaskType.LOGISTIC_REGRESSION, cfg, mesh)
    ref = _single_device_reference(X, y, cfg)
    np.testing.assert_allclose(np.asarray(res.coefficients)[:20], ref, atol=1e-6)


def test_coefficients_are_model_sharded(rng, eight_devices):
    """The point of the axis: per-device coefficient memory ~ D / n_model."""
    X, y = _problem(rng, n=256, d=64)
    mesh = make_mesh2(2, 4)
    sharded, _, _ = shard_labeled_data_2d(
        LabeledData.build(X, y, dtype=jnp.float64), mesh
    )
    d_pad = sharded.X.n_cols
    res, _ = train_glm_feature_sharded(
        sharded, TaskType.LOGISTIC_REGRESSION, _cfg(), mesh
    )
    coef = res.coefficients
    assert coef.sharding.spec == jax.sharding.PartitionSpec(MODEL_AXIS)
    shard_rows = {s.data.shape[0] for s in coef.addressable_shards}
    assert shard_rows == {d_pad // 4}
    # the design matrix is block-sharded over BOTH axes
    xs = {s.data.shape for s in sharded.X.values.addressable_shards}
    assert xs == {(256 // 2, d_pad // 4)}


def test_warm_start_round_trip(rng, eight_devices):
    X, y = _problem(rng)
    cfg = _cfg()
    mesh = make_mesh2(2, 4)
    sharded, _, d_pad = shard_labeled_data_2d(
        LabeledData.build(X, y, dtype=jnp.float64), mesh
    )
    first, _ = train_glm_feature_sharded(
        sharded, TaskType.LOGISTIC_REGRESSION, cfg, mesh
    )
    warm = np.zeros(sharded.X.n_cols)  # padded width
    warm[: X.shape[1]] = np.asarray(first.coefficients)[: X.shape[1]]
    again, _ = train_glm_feature_sharded(
        sharded, TaskType.LOGISTIC_REGRESSION, cfg, mesh,
        initial_coefficients=warm,
    )
    assert int(again.iterations) <= int(first.iterations)
    # a fresh LBFGS history wanders slightly around the optimum: compare to the
    # converged solution loosely, not bitwise
    np.testing.assert_allclose(
        np.asarray(again.coefficients), np.asarray(first.coefficients), atol=1e-4
    )


def test_sparse_2d_matches_single_device(rng, eight_devices):
    """The wide-FE path: sparse COO shards its flat nnz axis over BOTH mesh
    axes (coefficients P("model"), scores P("data")) and solves to the same
    optimum as the single-device dense reference."""
    import scipy.sparse as sp

    X, y = _problem(rng, n=256, d=24)
    X = np.where(rng.random(X.shape) < 0.3, X, 0.0)
    cfg = _cfg()
    mesh = make_mesh2(2, 4)
    sharded, n0, d0 = shard_labeled_data_2d(
        LabeledData.build(sp.csr_matrix(X), y, dtype=jnp.float64), mesh
    )
    assert (n0, d0) == (256, 24)
    res, _ = train_glm_feature_sharded(sharded, TaskType.LOGISTIC_REGRESSION, cfg, mesh)
    w2d = np.asarray(res.coefficients)
    ref = _single_device_reference(X, y, cfg)
    np.testing.assert_allclose(w2d[:24], ref, atol=1e-8)
    # padded (never-referenced) feature columns see only the L2 term -> 0
    assert np.all(w2d[24:] == 0.0)


def test_sparse_2d_nnz_sharded(rng, eight_devices):
    """nnz arrays shard over the flattened 2-D mesh; the sorted-column layout
    is dropped (a global column sort would gather across shards)."""
    import scipy.sparse as sp

    X = sp.random(
        64, 16, density=0.2, random_state=np.random.RandomState(0)
    ).tocsr()
    y = np.zeros(64)
    mesh = make_mesh2(2, 4)
    sharded, _, _ = shard_labeled_data_2d(
        LabeledData.build(X, y, dtype=jnp.float64), mesh
    )
    Xs = sharded.X
    nnz_pad = Xs.vals.shape[0]
    assert nnz_pad % 8 == 0
    assert {s.data.shape[0] for s in Xs.vals.addressable_shards} == {nnz_pad // 8}
    assert Xs.col_order is None and Xs.cols_sorted is None
    assert Xs.rows_sorted
    # padding entries are inert: dense reconstruction matches scipy
    np.testing.assert_array_equal(
        np.asarray(Xs.to_dense())[:64, :16], X.toarray()
    )


def test_sparse_2d_unsorted_rows_refused(rng, eight_devices):
    """Feature-axis sharding refuses non-row-major sparse entry order: nnz
    padding appends at the last row id, which only preserves the sorted-rows
    invariant the sharded matvec asserts when rows already arrive sorted."""
    import dataclasses as dc

    import scipy.sparse as sp

    from photon_ml_tpu.data.matrix import SparseDesignMatrix

    X = sp.random(
        32, 8, density=0.3, random_state=np.random.RandomState(1)
    ).tocsr()
    sm = SparseDesignMatrix.from_scipy(X, dtype=jnp.float64)
    shuffled = dc.replace(
        sm,
        rows=sm.rows[::-1],
        cols=sm.cols[::-1],
        vals=sm.vals[::-1],
        rows_sorted=False,
    )
    data = LabeledData.build(
        shuffled, np.zeros(32), dtype=jnp.float64
    )
    mesh = make_mesh2(2, 4)
    with pytest.raises(ValueError, match="row-major"):
        shard_labeled_data_2d(data, mesh)
