"""Serving fleet (photon_ml_tpu/serving/fleet.py + transport.py): multi-model
routing with layered admission (per-tenant token buckets, per-model budgets,
priority classes), replica round-robin with overload failover, replica-at-a-
time rolling hot-swap with canary gating + blacklist, and the HTTP transport.

The load-bearing property throughout, inherited from the frontend tests: a
response served through ANY fleet layer is BITWISE what a direct engine call
on the same request against the serving generation returns.
"""

import dataclasses
import os
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu.io.checkpoint import save_checkpoint
from photon_ml_tpu.models.glm import Coefficients
from photon_ml_tpu.resilience import Retry, armed, corrupt_file
from photon_ml_tpu.serving import (
    FleetClient,
    FleetHTTPServer,
    FrontendConfig,
    GenerationWatcher,
    ModelRouter,
    Overloaded,
    QuotaExceeded,
    ReplicaSet,
    TenantQuota,
    TokenBucket,
    clear_engine_cache,
    decode_game_input,
    encode_game_input,
)

from tests.test_hotswap import build_models, corrupt_generation, make_req


@pytest.fixture(autouse=True)
def _fresh_engine_cache():
    clear_engine_cache()
    yield
    clear_engine_cache()


FAST_RETRY = Retry(max_attempts=3, base_delay=0.0, sleep=lambda s: None, seed=0)


def build_fleet(tmp_path, rng, n_replicas=2, name="m", subdir="ckpt", **kwargs):
    root = str(tmp_path / subdir)
    save_checkpoint(root, build_models(rng, 1.0), 1, keep_generations=8)
    rs = ReplicaSet.from_checkpoint(
        root, n_replicas, name=name, config=FrontendConfig(max_wait_ms=0.0),
        retry=kwargs.pop("retry", FAST_RETRY), **kwargs,
    )
    return root, rs


def poison_models(models):
    """Valid-checksum NaN poisoning: the trainer-bug class only the canary's
    live-score health gate can catch."""
    out = dict(models)
    fe = models["fixed"]
    glm = fe.model
    out["fixed"] = dataclasses.replace(
        fe,
        model=type(glm)(
            Coefficients(means=jnp.full_like(glm.coefficients.means, jnp.nan))
        ),
    )
    return out


# ------------------------------------------------------------- token bucket


def test_token_bucket_deterministic_refill():
    t = [0.0]
    b = TokenBucket(rate=2.0, burst=3.0, clock=lambda: t[0])
    assert [b.try_take() for _ in range(4)] == [True, True, True, False]
    t[0] = 1.0  # 2 tokens refilled
    assert b.try_take() and b.try_take() and not b.try_take()
    t[0] = 100.0  # refill clamps at burst
    assert [b.try_take() for _ in range(4)] == [True, True, True, False]


def test_token_bucket_validates():
    with pytest.raises(ValueError, match="burst"):
        TokenBucket(rate=1.0, burst=0.0, clock=time.monotonic)
    with pytest.raises(ValueError, match="rate"):
        TokenBucket(rate=-1.0, burst=1.0, clock=time.monotonic)


# ------------------------------------------------------------------ routing


def test_router_parity_and_round_robin(tmp_path, rng):
    _, rs = build_fleet(tmp_path, rng, n_replicas=3)
    router = ModelRouter()
    router.add_model("m", rs)
    try:
        reqs = [make_req(rng) for _ in range(6)]
        for req in reqs:
            out = router.score("m", req, timeout=30)
            direct = rs.replicas[0].engine.score(req)
            assert out.dtype == direct.dtype
            np.testing.assert_array_equal(out, direct)
        # round-robin spread the requests across every replica
        counts = [r.frontend.stats()["served"] for r in rs.replicas]
        assert counts == [2, 2, 2]
    finally:
        router.close()


def test_router_unknown_model_and_duplicate_registration(tmp_path, rng):
    _, rs = build_fleet(tmp_path, rng, n_replicas=1)
    router = ModelRouter()
    router.add_model("m", rs)
    try:
        with pytest.raises(KeyError, match="unknown model"):
            router.submit("nope", make_req(rng))
        with pytest.raises(ValueError, match="already registered"):
            router.add_model("m", rs)
        with pytest.raises(ValueError, match="priority"):
            router.add_model("m2", rs, priority="urgentest")
    finally:
        router.close()


def test_multi_model_share_one_engine_cache(tmp_path, rng):
    """Two models registered from the same committed bytes resolve to the
    SAME engine object (content-keyed get_engine cache): one set of device
    tables, one compiled program family."""
    _, rs_a = build_fleet(tmp_path, rng, n_replicas=1, name="a", subdir="ckpt-a")
    root_b = str(tmp_path / "ckpt-b")
    # a different RANDOM model would differ; same seed reproduces the bytes
    save_checkpoint(
        root_b, build_models(np.random.default_rng(12345), 1.0), 1,
        keep_generations=8,
    )
    save_checkpoint(
        str(tmp_path / "ckpt-c"), build_models(np.random.default_rng(12345), 1.0), 1,
        keep_generations=8,
    )
    rs_b = ReplicaSet.from_checkpoint(
        root_b, 1, name="b", config=FrontendConfig(max_wait_ms=0.0))
    rs_c = ReplicaSet.from_checkpoint(
        str(tmp_path / "ckpt-c"), 1, name="c", config=FrontendConfig(max_wait_ms=0.0))
    try:
        assert rs_b.replicas[0].engine is rs_c.replicas[0].engine
        assert rs_a.replicas[0].engine is not rs_b.replicas[0].engine
    finally:
        rs_a.close()
        rs_b.close()
        rs_c.close()


def test_tenant_quota_sheds_distinct_from_overload(tmp_path, rng):
    _, rs = build_fleet(tmp_path, rng, n_replicas=1)
    router = ModelRouter()
    router.add_model(
        "m", rs,
        tenant_quota=TenantQuota(rate=0.0, burst=2.0),
        tenant_quotas={"vip": TenantQuota(rate=0.0, burst=100.0)},
    )
    try:
        req = make_req(rng)
        # default-quota tenant: burst 2 admits, third sheds as QUOTA
        router.score("m", req, tenant="t1", timeout=30)
        router.score("m", req, tenant="t1", timeout=30)
        with pytest.raises(QuotaExceeded, match="exceeded its quota"):
            router.submit("m", req, tenant="t1")
        # buckets are per tenant: t2 and the vip override still admit
        router.score("m", req, tenant="t2", timeout=30)
        for _ in range(5):
            router.score("m", req, tenant="vip", timeout=30)
        stats = router.stats()
        assert stats["shed_quota"] == 1
        assert stats.get("shed_overload", 0) == 0
        kinds = [i.kind for i in router.incidents]
        assert kinds.count("quota-shed") == 1
        assert "overload" not in kinds
    finally:
        router.close()


def test_admission_budget_sheds_as_overload(tmp_path, rng):
    from tests.test_serving_frontend import GatedEngine

    _, rs = build_fleet(tmp_path, rng, n_replicas=1)
    # gate the replica's engine so in-flight requests accumulate
    fe = rs.replicas[0].frontend
    gated = GatedEngine(fe.engine, gated=True)
    fe.install_engine(gated, fe.generation)
    router = ModelRouter()
    router.add_model("m", rs, admission_budget=2)
    try:
        req = make_req(rng)
        futs = [router.submit("m", req) for _ in range(2)]
        with pytest.raises(Overloaded, match="admission budget"):
            router.submit("m", req)
        assert router.stats()["shed_overload"] == 1
        assert any(i.kind == "overload" for i in router.incidents)
        gated.gate.set()
        for f in futs:  # everything admitted is served
            assert f.result(30) is not None
        # in-flight accounting drains via done-callbacks: admission reopens
        deadline = time.monotonic() + 10.0
        while router.stats()["inflight"] and time.monotonic() < deadline:
            time.sleep(0.01)
        out = router.score("m", req, timeout=30)
        np.testing.assert_array_equal(out, gated.inner.score(req))
    finally:
        gated.gate.set()
        router.close()


def test_priority_classes_partition_fleet_budget(tmp_path, rng):
    from tests.test_serving_frontend import GatedEngine

    _, rs = build_fleet(tmp_path, rng, n_replicas=1)
    fe = rs.replicas[0].frontend
    gated = GatedEngine(fe.engine, gated=True)
    fe.install_engine(gated, fe.generation)
    router = ModelRouter(fleet_budget=4)
    router.add_model("interactive", rs, priority="interactive")
    router.add_model("batch", rs, priority="batch")
    try:
        req = make_req(rng)
        futs = [router.submit("interactive", req) for _ in range(2)]
        # fleet at 2/4 in flight = the batch class's 50% admission cutoff:
        # batch sheds while interactive still admits
        with pytest.raises(Overloaded, match="priority 'batch'"):
            router.submit("batch", req)
        futs += [router.submit("interactive", req) for _ in range(2)]
        # ... until the full budget is gone for everyone
        with pytest.raises(Overloaded, match="priority 'interactive'"):
            router.submit("interactive", req)
        gated.gate.set()
        for f in futs:
            assert f.result(30) is not None
    finally:
        gated.gate.set()
        router.close()


def test_replica_overload_fails_over_to_next(tmp_path, rng):
    """One replica at queue depth must not shed the fleet: the router's
    round-robin retries the other replica before propagating Overloaded."""
    from tests.test_serving_frontend import GatedEngine

    root = str(tmp_path / "ckpt")
    save_checkpoint(root, build_models(rng, 1.0), 1, keep_generations=8)
    rs = ReplicaSet.from_checkpoint(
        root, 2, name="m",
        config=FrontendConfig(max_wait_ms=0.0, max_queue_depth=1),
    )
    router = ModelRouter()
    router.add_model("m", rs)
    try:
        req = make_req(rng)
        # wedge replica 0: one in-flight + one queued = at depth
        fe0 = rs.replicas[0].frontend
        gated = GatedEngine(fe0.engine, gated=True)
        fe0.install_engine(gated, fe0.generation)
        wedged = fe0.submit(req)
        assert gated.entered.wait(10.0)
        queued = fe0.submit(req)
        # router submissions starting at replica 0 fail over to replica 1
        outs = [router.score("m", req, timeout=30) for _ in range(3)]
        direct = rs.replicas[1].engine.score(req)
        for out in outs:
            np.testing.assert_array_equal(out, direct)
        # the failed-over sheds are still visible in replica 0's log
        assert rs.replicas[0].frontend.stats()["shed_overload"] >= 1
        gated.gate.set()
        assert wedged.result(30) is not None and queued.result(30) is not None
    finally:
        gated.gate.set()
        router.close()


# --------------------------------------------------------- rolling hot-swap


def test_rolling_swap_converges_all_replicas_bitwise(tmp_path, rng):
    root, rs = build_fleet(tmp_path, rng, n_replicas=3)
    router = ModelRouter()
    router.add_model("m", rs)
    try:
        reqs = [make_req(rng) for _ in range(4)]
        for req in reqs:  # live shapes + mirror pool
            router.score("m", req, timeout=30)
        save_checkpoint(root, build_models(rng, 2.0), 2, keep_generations=8)
        assert rs.check_once() is True
        assert rs.generations == [2, 2, 2] and rs.converged
        assert rs.rollouts_completed == 1
        eng2 = rs.replicas[0].engine
        for req in reqs:
            out = router.score("m", req, timeout=30)
            assert out.dtype == eng2.score(req).dtype
            np.testing.assert_array_equal(out, eng2.score(req))
        # nothing new -> no-op
        assert rs.check_once() is False
    finally:
        router.close()


def test_rolling_swap_spans_generations_under_traffic(tmp_path, rng):
    """Concurrent traffic across the roll: every response bitwise matches the
    engine of the generation that served it; zero drops."""
    root, rs = build_fleet(tmp_path, rng, n_replicas=2)
    router = ModelRouter()
    router.add_model("m", rs)
    engines = {1: rs.replicas[0].engine}
    reqs = [make_req(rng) for _ in range(4)]
    served, errors = [], []
    stop = threading.Event()

    def client(cid):
        i = 0
        while not stop.is_set():
            req = reqs[(cid + i) % len(reqs)]
            i += 1
            try:
                fut = router.submit("m", req)
                served.append((req, fut.result(30), fut.generation))
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

    threads = [threading.Thread(target=client, args=(c,)) for c in range(2)]
    try:
        for req in reqs:
            router.score("m", req, timeout=30)
        for t in threads:
            t.start()
        deadline = time.monotonic() + 30.0
        while len(served) < 5 and time.monotonic() < deadline:
            time.sleep(0.01)
        save_checkpoint(root, build_models(rng, 2.0), 2, keep_generations=8)
        assert rs.check_once() is True
        deadline = time.monotonic() + 30.0
        while not any(g == 2 for _, _, g in list(served)) and (
            time.monotonic() < deadline
        ):
            time.sleep(0.01)
        stop.set()
        for t in threads:
            t.join(30)
        engines[2] = rs.replicas[0].engine
        assert not errors
        gens = {g for _, _, g in served}
        assert 1 in gens and 2 in gens  # the stream spanned the roll
        for req, out, g in served:
            direct = engines[g].score(req)
            assert out.dtype == direct.dtype
            np.testing.assert_array_equal(out, direct)
    finally:
        stop.set()
        router.close()


def test_canary_rejects_poisoned_generation_and_blacklists(tmp_path, rng):
    """A NaN-poisoned commit passes every checksum; the canary's live-score
    health gate catches it, flips the canary BACK, blacklists fleet-wide."""
    root, rs = build_fleet(tmp_path, rng, n_replicas=3)
    router = ModelRouter()
    router.add_model("m", rs)
    try:
        reqs = [make_req(rng) for _ in range(3)]
        for req in reqs:
            router.score("m", req, timeout=30)
        before = router.score("m", reqs[0], timeout=30)
        save_checkpoint(root, poison_models(build_models(rng, 2.0)), 2,
                        keep_generations=8)
        assert rs.check_once() is False
        assert rs.generations == [1, 1, 1]  # canary flipped back
        assert rs.bad_generations == {2}
        assert rs.rollbacks == 1
        assert any(i.kind == "canary-reject" for i in rs.incidents)
        # serving never blinked, and the bad generation is never re-tried
        np.testing.assert_array_equal(router.score("m", reqs[0], timeout=30), before)
        assert rs.check_once() is False
        # a LATER good generation still rolls
        save_checkpoint(root, build_models(rng, 3.0), 3, keep_generations=8)
        assert rs.check_once() is True
        assert rs.generations == [3, 3, 3]
    finally:
        router.close()


def test_canary_serving_path_parity_is_gated(tmp_path, rng):
    """The canary gate's OTHER clause: live scores through the flipped canary
    must be bitwise the candidate engine's direct answer. Sabotage the
    candidate's serving path (an engine wrapper that perturbs one ulp) and
    the rollout must reject."""
    from photon_ml_tpu.serving import fleet as fleet_mod

    root, rs = build_fleet(tmp_path, rng, n_replicas=2)
    try:
        req = make_req(rng)
        rs.replicas[0].frontend.score(req, timeout=30)
        rs._mirror.append(("score", True, req))
        save_checkpoint(root, build_models(rng, 2.0), 2, keep_generations=8)

        real_get_engine = fleet_mod.get_engine

        class SkewedEngine:
            """Engine whose FRONTEND-visible scores differ from its direct
            scores by one ulp — a broken serving path in miniature."""

            def __init__(self, inner):
                self.inner = inner
                self.mesh = inner.mesh
                self.min_batch_pad = inner.min_batch_pad
                self.precision = inner.precision
                self.fingerprint = inner.fingerprint + "-skewed"
                self._direct = True

            def bucket(self, n):
                return self.inner.bucket(n)

            def score(self, data, include_offsets=True):
                out = self.inner.score(data, include_offsets=include_offsets)
                import threading as _t

                if _t.current_thread().name.startswith("photon-serving-dispatch"):
                    return np.nextafter(out, np.inf)  # live path perturbed
                return out

            def predict(self, data):
                return self.inner.predict(data)

        def skewing_get_engine(model, **kwargs):
            return SkewedEngine(real_get_engine(model, **kwargs))

        fleet_mod.get_engine = skewing_get_engine
        try:
            assert rs.check_once() is False
        finally:
            fleet_mod.get_engine = real_get_engine
        assert rs.generations == [1, 1]
        assert rs.bad_generations == {2}
        rejects = [i for i in rs.incidents if i.kind == "canary-reject"]
        assert rejects and "serving-path parity" in rejects[0].cause
    finally:
        rs.close()


def test_integrity_failure_rolls_back_and_blacklists(tmp_path, rng):
    root, rs = build_fleet(tmp_path, rng, n_replicas=2)
    try:
        req = make_req(rng)
        rs.replicas[0].frontend.score(req, timeout=30)
        gen2 = save_checkpoint(root, build_models(rng, 2.0), 2, keep_generations=8)
        corrupt_generation(gen2)
        assert rs.check_once() is False
        assert rs.generations == [1, 1]
        assert rs.bad_generations == {2}
        assert any(i.kind == "fleet-rollback" for i in rs.incidents)
    finally:
        rs.close()


def test_transient_fault_retries_without_blacklist(tmp_path, rng):
    """A transient I/O fault exhausting the retry budget rolls back WITHOUT
    blacklisting (the environment failed, not the generation); the next poll
    rolls."""
    root, rs = build_fleet(tmp_path, rng, n_replicas=2)
    try:
        req = make_req(rng)
        rs.replicas[0].frontend.score(req, timeout=30)
        save_checkpoint(root, build_models(rng, 2.0), 2, keep_generations=8)
        with armed("serve.fleet.canary:raise:1x*"):
            assert rs.check_once() is False
        assert rs.bad_generations == set()
        assert rs.generations == [1, 1]
        assert rs.check_once() is True  # I/O recovered -> rolls
        assert rs.generations == [2, 2]
        # a transient absorbed WITHIN the budget doesn't even roll back
        save_checkpoint(root, build_models(rng, 3.0), 3, keep_generations=8)
        with armed("serve.fleet.canary:raise:1"):
            assert rs.check_once() is True
        assert rs.generations == [3, 3]
    finally:
        rs.close()


def test_canary_shed_under_load_rolls_back_without_blacklist(tmp_path, rng):
    """A canary evaluation shed (Overloaded/DeadlineExceeded from the
    canary's live queue) is LOAD, not bad bytes: roll back, do NOT
    blacklist — the next poll (queue drained) must still roll the
    generation. (Review finding: these RuntimeErrors used to blacklist a
    healthy generation forever.)"""
    from photon_ml_tpu.serving import Replica, ServingFrontend, get_engine
    from photon_ml_tpu.serving.hotswap import (
        model_from_state,
        newest_valid_generation,
    )

    root = str(tmp_path / "ckpt")
    save_checkpoint(root, build_models(rng, 1.0), 1, keep_generations=8)
    _, state = newest_valid_generation(root)
    engine = get_engine(model_from_state(state))
    # the canary's config sheds EVERY submission at admission (expired
    # deadline) — the shape of a queue under crushing load
    canary_fe = ServingFrontend(
        engine, FrontendConfig(max_wait_ms=0.0, default_deadline_ms=-1.0),
        generation=1,
    )
    other_fe = ServingFrontend(engine, FrontendConfig(max_wait_ms=0.0), generation=1)
    rs = ReplicaSet(
        "m", root,
        [Replica("m/r0", canary_fe), Replica("m/r1", other_fe)],
        retry=FAST_RETRY,
    )
    try:
        rs._mirror.append(("score", True, make_req(rng)))
        save_checkpoint(root, build_models(rng, 2.0), 2, keep_generations=8)
        assert rs.check_once() is False
        assert rs.bad_generations == set()  # the load was at fault, not gen 2
        assert rs.generations == [1, 1]  # canary flipped back
        rollback = [i for i in rs.incidents if i.kind == "fleet-rollback"]
        assert rollback and "will retry generation 2" in rollback[0].action
        # load clears -> the very next poll rolls the same generation
        canary_fe.config.default_deadline_ms = None
        assert rs.check_once() is True
        assert rs.generations == [2, 2]
    finally:
        rs.close()


def test_crash_mid_roll_leaves_consistent_fleet_then_converges(tmp_path, rng):
    """A crash between replica flips (serve.fleet.roll) leaves a MIXED fleet
    in which each replica serves its own generation bitwise-correctly, does
    NOT blacklist (the generation passed canary), and the next poll
    converges the stragglers."""
    from photon_ml_tpu.resilience import InjectedCrash

    root, rs = build_fleet(tmp_path, rng, n_replicas=3)
    try:
        reqs = [make_req(rng) for _ in range(3)]
        for i, req in enumerate(reqs):
            rs.replicas[i % 3].frontend.score(req, timeout=30)
            rs._mirror.append(("score", True, req))
        eng1 = rs.replicas[0].engine
        save_checkpoint(root, build_models(rng, 2.0), 2, keep_generations=8)
        with armed("serve.fleet.roll:crash:1"):
            assert rs.check_once() is False
        # canary flipped, the rest did not: mixed but CONSISTENT
        assert sorted(rs.generations) == [1, 1, 2]
        assert rs.bad_generations == set()
        eng2 = next(r.engine for r in rs.replicas if r.generation == 2)
        for r in rs.replicas:
            out = r.frontend.score(reqs[0], timeout=30)
            expected = (eng2 if r.generation == 2 else eng1).score(reqs[0])
            np.testing.assert_array_equal(out, expected)
        assert any(i.kind == "fleet-rollback" for i in rs.incidents)
        # next poll converges the stragglers
        assert rs.check_once() is True
        assert rs.generations == [2, 2, 2]
    finally:
        rs.close()


def test_generation_watcher_drives_fleet_rollouts(tmp_path, rng):
    """GenerationWatcher's manager duck type: a ReplicaSet (and the router)
    plug in unchanged."""
    root, rs = build_fleet(tmp_path, rng, n_replicas=2)
    router = ModelRouter()
    router.add_model("m", rs)
    try:
        req = make_req(rng)
        router.score("m", req, timeout=30)
        with GenerationWatcher(router, poll_interval_s=0.05):
            save_checkpoint(root, build_models(rng, 2.0), 2, keep_generations=8)
            deadline = time.monotonic() + 30.0
            while not (rs.converged and rs.generations[0] == 2) and (
                time.monotonic() < deadline
            ):
                time.sleep(0.02)
        assert rs.generations == [2, 2]
        out = router.score("m", req, timeout=30)
        np.testing.assert_array_equal(out, rs.replicas[0].engine.score(req))
    finally:
        router.close()


def test_replica_set_validates(tmp_path, rng):
    root = str(tmp_path / "ckpt")
    with pytest.raises(FileNotFoundError, match="no valid checkpoint"):
        ReplicaSet.from_checkpoint(root, 2)
    save_checkpoint(root, build_models(rng, 1.0), 1, keep_generations=8)
    with pytest.raises(ValueError, match="n_replicas"):
        ReplicaSet.from_checkpoint(root, 0)
    with pytest.raises(ValueError, match="at least one replica"):
        ReplicaSet("m", root, [])


# ---------------------------------------------------------------- transport


def test_codec_round_trips_bitwise(rng):
    req = make_req(rng, 9)
    body = encode_game_input(req, include_offsets=False)
    # JSON round trip: exactly what crosses the wire
    import json as _json

    decoded, include_offsets = decode_game_input(_json.loads(_json.dumps(body)))
    assert include_offsets is False
    assert sorted(decoded.features) == sorted(req.features)
    np.testing.assert_array_equal(
        decoded.features["global"], np.asarray(req.features["global"])
    )
    got = decoded.features["re_shard"]
    want = req.features["re_shard"].tocsr()
    assert got.dtype == want.dtype
    np.testing.assert_array_equal(got.data, want.data)
    np.testing.assert_array_equal(got.indices, want.indices)
    np.testing.assert_array_equal(got.indptr, want.indptr)
    assert decoded.offsets.dtype == np.asarray(req.offsets).dtype
    np.testing.assert_array_equal(decoded.offsets, req.offsets)
    np.testing.assert_array_equal(decoded.id_columns["userId"], req.id_columns["userId"])


def test_codec_object_str_ids_convert_and_mixed_refused(rng):
    from photon_ml_tpu.serving.transport import decode_array, encode_array

    # Avro readers hand string entity ids back as object-of-str arrays:
    # those must cross the wire (as their '<U*' form, same ids)
    ids = np.asarray(["u1", "u22", "u3"], dtype=object)
    got = decode_array(encode_array(ids))
    assert got.dtype.kind == "U"
    assert got.tolist() == ["u1", "u22", "u3"]
    # anything else object-typed stays refused — no pickling on the wire
    with pytest.raises(TypeError, match="object arrays"):
        encode_array(np.asarray(["a", 1], dtype=object))


def test_http_score_predict_bitwise_and_error_mapping(tmp_path, rng):
    from photon_ml_tpu.serving import DeadlineExceeded

    _, rs = build_fleet(tmp_path, rng, n_replicas=2)
    router = ModelRouter()
    router.add_model(
        "m", rs, tenant_quotas={"capped": TenantQuota(rate=0.0, burst=1.0)}
    )
    try:
        with FleetHTTPServer(router, port=0) as srv:
            client = FleetClient(srv.host, srv.port)
            assert client.healthy()
            req = make_req(rng)
            eng = rs.replicas[0].engine
            out, gen = client.score("m", req)
            direct = eng.score(req)
            assert gen == 1
            assert out.dtype == direct.dtype
            np.testing.assert_array_equal(out, direct)
            pred, _ = client.predict("m", req)
            dpred = eng.predict(req)
            assert pred.dtype == dpred.dtype
            np.testing.assert_array_equal(pred, dpred)
            # include_offsets rides the body
            out_no_off, _ = client.score("m", req, include_offsets=False)
            np.testing.assert_array_equal(
                out_no_off, eng.score(req, include_offsets=False)
            )
            # error taxonomy over the wire
            with pytest.raises(KeyError):
                client.score("nope", req)
            client.score("m", req, tenant="capped")
            with pytest.raises(QuotaExceeded):
                client.score("m", req, tenant="capped")
            with pytest.raises(DeadlineExceeded):
                client.score("m", req, deadline_ms=0.0)
            assert client.models() == {"m": {"generations": [1, 1]}}
            stats = client.stats()
            assert stats["shed_quota"] == 1
            assert stats["models"]["m"]["generations"] == [1, 1]
    finally:
        router.close()


def test_http_serves_across_rolling_swap(tmp_path, rng):
    root, rs = build_fleet(tmp_path, rng, n_replicas=2)
    router = ModelRouter()
    router.add_model("m", rs)
    try:
        with FleetHTTPServer(router, port=0) as srv:
            client = FleetClient(srv.host, srv.port)
            req = make_req(rng)
            out1, gen1 = client.score("m", req)
            assert gen1 == 1
            save_checkpoint(root, build_models(rng, 2.0), 2, keep_generations=8)
            assert rs.check_once() is True
            out2, gen2 = client.score("m", req)
            assert gen2 == 2
            direct = rs.replicas[0].engine.score(req)
            assert out2.dtype == direct.dtype
            np.testing.assert_array_equal(out2, direct)
            assert not np.array_equal(out1, out2)
    finally:
        router.close()


# ----------------------------------------------------------- fleet CLI mode


def test_serving_driver_fleet_flags_parse(tmp_path):
    """The shared --fleet-* flag block rides add_serving_arguments (the
    end-to-end fleet replay lives in tests/test_cli_drivers.py, on the
    trained fixture)."""
    from photon_ml_tpu.cli import serving_driver

    args = serving_driver.build_arg_parser().parse_args([
        "--checkpoint-directory", str(tmp_path / "ckpt"),
        "--input-data-directories", str(tmp_path / "in"),
        "--root-output-directory", str(tmp_path / "out"),
        "--feature-shard-configurations", "name=global,feature.bags=features",
        "--fleet-replicas", "2",
        "--fleet-http-port", "0",
    ])
    assert args.fleet_replicas == 2 and args.fleet_http_port == 0


def test_sheds_by_cause_breakout_shapes():
    from photon_ml_tpu.cli.serving_driver import _served_by_generation, _sheds_by_cause

    stats = {
        "shed_overload": 1,
        "shed_deadline": 2,
        "served_by_generation": {1: 5},
        "models": {
            "m": {
                "shed_overload": 3,
                "shed_shutdown": 4,
                "served_by_generation": {"1": 2, "2": 7},
            }
        },
        "shed_quota": 6,
    }
    assert _sheds_by_cause(stats) == {
        "overload": 4, "deadline": 2, "quota": 6, "shutdown": 4,
    }
    assert _served_by_generation(stats) == {1: 7, 2: 7}


# --------------------------------------------------------------------------
# durable canary blacklist: the verdict lives IN the generational store
# (io/checkpoint.record_generation_blacklist), so independent serving
# processes booted on the same store agree on rejected generations without
# any channel between them — one fleet's canary spares every other.
# --------------------------------------------------------------------------


def test_blacklist_record_and_load_round_trip(tmp_path):
    from photon_ml_tpu.io.checkpoint import (
        load_generation_blacklist,
        record_generation_blacklist,
    )

    root = str(tmp_path / "store")
    assert load_generation_blacklist(root) == {}  # missing dir = empty
    path = record_generation_blacklist(root, 7, "CanaryMismatch: poisoned")
    assert path is not None and os.path.exists(path)
    # ONE file is the whole commit (digest embedded): no sidecar whose torn
    # pairing with the content could drop a verdict
    assert sorted(os.listdir(os.path.dirname(path))) == ["gen-00000007.json"]
    record_generation_blacklist(root, 9, "corrupt")
    assert load_generation_blacklist(root) == {
        7: "CanaryMismatch: poisoned", 9: "corrupt",
    }
    # re-recording the same generation is idempotent (last verdict wins)
    record_generation_blacklist(root, 7, "CanaryMismatch: again")
    assert load_generation_blacklist(root)[7] == "CanaryMismatch: again"


def test_blacklist_damaged_entry_is_ignored_not_adopted(tmp_path):
    from photon_ml_tpu.io.checkpoint import (
        load_generation_blacklist,
        record_generation_blacklist,
    )

    root = str(tmp_path / "store")
    p7 = record_generation_blacklist(root, 7, "bad")
    p8 = record_generation_blacklist(root, 8, "also bad")
    corrupt_file(p7)  # bit-rot the entry AFTER its digest was embedded
    verdicts = load_generation_blacklist(root)
    assert 7 not in verdicts  # damaged entry treated as absent, loudly logged
    assert verdicts == {8: "also bad"}
    # a torn (truncated) entry is also ignored
    blob = open(p8, "rb").read()
    with open(p8, "wb") as f:
        f.write(blob[: len(blob) // 2])
    assert load_generation_blacklist(root) == {}


def test_canary_verdict_is_durable_across_independent_fleets(tmp_path, rng):
    """Fleet A's canary rejects a NaN-poisoned generation; fleet B, a fresh
    set of replicas booted LATER on the same store (a different process in
    production), must skip it at bootstrap without its own canary attempt."""
    from photon_ml_tpu.io.checkpoint import load_generation_blacklist

    root, rs_a = build_fleet(tmp_path, rng, n_replicas=2)
    router = ModelRouter()
    router.add_model("m", rs_a)
    try:
        for _ in range(3):
            router.score("m", make_req(rng), timeout=30)
        save_checkpoint(root, poison_models(build_models(rng, 2.0)), 2,
                        keep_generations=8)
        assert rs_a.check_once() is False
        assert rs_a.bad_generations == {2}
        # the verdict is on disk, in the store
        assert 2 in load_generation_blacklist(root)
    finally:
        router.close()

    # an INDEPENDENT fleet adopts the verdict at bootstrap: no canary run,
    # no attempt ever made on the poisoned generation
    rs_b = ReplicaSet.from_checkpoint(
        root, 2, name="b", config=FrontendConfig(max_wait_ms=0.0),
        retry=FAST_RETRY,
    )
    try:
        assert 2 in rs_b.bad_generations
        assert rs_b.check_once() is False  # nothing eligible
        assert rs_b.generations == [1, 1]
        assert rs_b.rollbacks == 0  # the verdict cost B nothing
        # and a verdict recorded by ANOTHER process AFTER B booted is adopted
        # at the next poll (check_once re-reads the store)
        from photon_ml_tpu.io.checkpoint import record_generation_blacklist

        save_checkpoint(root, build_models(rng, 3.0), 3, keep_generations=8)
        record_generation_blacklist(root, 3, "rejected elsewhere")
        assert rs_b.check_once() is False
        assert 3 in rs_b.bad_generations
    finally:
        rs_b.close()


def test_hotswap_manager_reads_durable_blacklist_at_bootstrap(tmp_path, rng):
    from photon_ml_tpu.io.checkpoint import record_generation_blacklist
    from photon_ml_tpu.serving.hotswap import serve_from_checkpoint

    root = str(tmp_path / "ckpt")
    save_checkpoint(root, build_models(rng, 1.0), 1, keep_generations=8)
    save_checkpoint(root, build_models(rng, 2.0), 2, keep_generations=8)
    record_generation_blacklist(root, 2, "rejected by a fleet canary")
    frontend, manager = serve_from_checkpoint(
        root, config=FrontendConfig(max_wait_ms=0.0)
    )
    try:
        assert 2 in manager.bad_generations
        assert manager.check_once() is False  # gen-2 is never attempted
        assert frontend.generation == 1
        # a later good generation still swaps in
        save_checkpoint(root, build_models(rng, 3.0), 3, keep_generations=8)
        assert manager.check_once() is True
        assert frontend.generation == 3
    finally:
        frontend.close()


def test_durable_blacklist_can_be_opted_out(tmp_path, rng):
    """durable_blacklist=False keeps the verdict process-local (e.g. a
    read-only mirror of someone else's store)."""
    from photon_ml_tpu.io.checkpoint import load_generation_blacklist

    root, rs = build_fleet(tmp_path, rng, n_replicas=2, durable_blacklist=False)
    router = ModelRouter()
    router.add_model("m", rs)
    try:
        for _ in range(3):
            router.score("m", make_req(rng), timeout=30)
        save_checkpoint(root, poison_models(build_models(rng, 2.0)), 2,
                        keep_generations=8)
        assert rs.check_once() is False
        assert rs.bad_generations == {2}  # in-memory verdict still works
        assert load_generation_blacklist(root) == {}  # nothing written
    finally:
        router.close()


def test_blacklist_opt_out_covers_bootstrap_too(tmp_path, rng):
    """durable_blacklist=False must also skip the verdict at the BOOT
    generation pick: an operator debugging a rejected generation can serve
    it deliberately."""
    from photon_ml_tpu.io.checkpoint import record_generation_blacklist
    from photon_ml_tpu.serving.hotswap import serve_from_checkpoint

    root = str(tmp_path / "ckpt")
    save_checkpoint(root, build_models(rng, 1.0), 1, keep_generations=8)
    save_checkpoint(root, build_models(rng, 2.0), 2, keep_generations=8)
    record_generation_blacklist(root, 2, "rejected elsewhere")
    # default: the verdict holds at bootstrap
    fe, _ = serve_from_checkpoint(root, config=FrontendConfig(max_wait_ms=0.0))
    try:
        assert fe.generation == 1
    finally:
        fe.close()
    # explicit opt-out: the newest generation serves despite the verdict
    fe2, mgr2 = serve_from_checkpoint(
        root, config=FrontendConfig(max_wait_ms=0.0), durable_blacklist=False
    )
    try:
        assert fe2.generation == 2
        assert mgr2.bad_generations == set()
    finally:
        fe2.close()
    rs = ReplicaSet.from_checkpoint(
        root, 1, name="opt-out", config=FrontendConfig(max_wait_ms=0.0),
        retry=FAST_RETRY, durable_blacklist=False,
    )
    try:
        assert rs.generations == [2]
    finally:
        rs.close()
