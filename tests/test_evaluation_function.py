"""GameEstimatorEvaluationFunction + end-to-end Bayesian tuning over GAME fits
(reference GameEstimatorEvaluationFunctionTest + runHyperparameterTuning path,
GameTrainingDriver.scala:643-674)."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.game_data import GameInput
from photon_ml_tpu.estimators.config import (
    CoordinateConfiguration,
    FixedEffectDataConfiguration,
)
from photon_ml_tpu.estimators.evaluation_function import GameEstimatorEvaluationFunction
from photon_ml_tpu.estimators.game_estimator import GameEstimator
from photon_ml_tpu.evaluation.evaluators import EvaluatorType
from photon_ml_tpu.hyperparameter import GaussianProcessSearch, RandomSearch
from photon_ml_tpu.optimization.common import OptimizerConfig
from photon_ml_tpu.optimization.config import (
    GLMOptimizationConfiguration,
    RegularizationContext,
)
from photon_ml_tpu.types import OptimizerType, RegularizationType, TaskType


def _data(rng, n=400, d=6, w=None):
    # Train/val pairs must share the SAME true coefficient vector w — with a
    # fresh w per split, validation AUC of a model fit on train is arbitrary.
    if w is None:
        w = rng.normal(size=d)
    X = rng.normal(size=(n, d))
    p = 1 / (1 + np.exp(-(X @ w)))
    y = (rng.random(n) < p).astype(np.float64)
    return GameInput(features={"global": X}, labels=y), w


def _estimator(reg_type=RegularizationType.L2, alpha=None):
    cfg = GLMOptimizationConfiguration(
        optimizer_config=OptimizerConfig(
            optimizer_type=OptimizerType.LBFGS, max_iterations=60
        ),
        regularization_context=RegularizationContext(reg_type, alpha),
        regularization_weight=1.0,
    )
    return GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinate_configurations={
            "global": CoordinateConfiguration(FixedEffectDataConfiguration("global"), cfg)
        },
        validation_evaluators=[EvaluatorType.AUC],
        dtype=jnp.float64,
    )


def test_vector_round_trip(rng):
    est = _estimator()
    fn = GameEstimatorEvaluationFunction(
        est,
        {c: est.coordinate_configurations[c].optimization_config for c in est.coordinate_configurations},
        None,
        None,
        is_opt_max=True,
    )
    assert fn.num_params == 1
    configs = fn.vector_to_configuration(np.array([np.log(10.0)]))
    assert configs["global"].regularization_weight == pytest.approx(10.0)
    vec = fn.configuration_to_vector(configs)
    np.testing.assert_allclose(vec, [np.log(10.0)])


def test_elastic_net_two_dims():
    est = _estimator(RegularizationType.ELASTIC_NET, alpha=0.5)
    fn = GameEstimatorEvaluationFunction(
        est,
        {c: est.coordinate_configurations[c].optimization_config for c in est.coordinate_configurations},
        None,
        None,
        is_opt_max=True,
    )
    assert fn.num_params == 2
    configs = fn.vector_to_configuration(np.array([np.log(2.0), 0.25]))
    assert configs["global"].regularization_weight == pytest.approx(2.0)
    assert configs["global"].regularization_context.elastic_net_alpha == 0.25
    assert configs["global"].l1_weight == pytest.approx(0.25 * 2.0)


def test_evaluation_runs_fit_and_negates_max_metric(rng):
    train, w = _data(rng)
    val, _ = _data(rng, w=w)
    est = _estimator()
    fn = GameEstimatorEvaluationFunction(
        est,
        {c: est.coordinate_configurations[c].optimization_config for c in est.coordinate_configurations},
        train,
        val,
        is_opt_max=True,  # AUC maximizes
    )
    value, result = fn(np.array([0.5]))
    assert value < 0  # negated AUC; AUC of a real model on separable-ish data > 0
    assert -value == pytest.approx(result.best_metric)
    obs = fn.convert_observations([result])
    assert len(obs) == 1
    assert 0.0 <= obs[0][0][0] <= 1.0


def test_random_search_over_game(rng):
    train, w = _data(rng, n=300)
    val, _ = _data(rng, n=300, w=w)
    est = _estimator()
    fn = GameEstimatorEvaluationFunction(
        est,
        {c: est.coordinate_configurations[c].optimization_config for c in est.coordinate_configurations},
        train,
        val,
        is_opt_max=True,
    )
    rs = RandomSearch(fn.num_params, fn, seed=11)
    results = rs.find(3)
    assert len(results) == 3
    aucs = [r.best_metric for r in results]
    assert all(0.4 < a <= 1.0 for a in aucs)
