"""Off-heap index store tests: build/load round-trip, partitioning, collisions,
reverse lookup, IndexMap-surface compatibility (PalDBIndexMap(Builder/Loader)
IntegTest pattern)."""

import numpy as np
import pytest

from photon_ml_tpu.data.index_map import feature_key
from photon_ml_tpu.data.offheap_index import (
    OffHeapIndexMap,
    OffHeapIndexMapBuilder,
    _fnv1a,
)


@pytest.fixture(params=[1, 4])
def store(request, tmp_path):
    keys = [feature_key(f"f{i}", f"t{i % 3}") for i in range(500)]
    builder = OffHeapIndexMapBuilder(str(tmp_path / "store"), num_partitions=request.param)
    builder.put_all(keys)
    return builder.build(), sorted(set(keys))


class TestOffHeapIndexMap:
    def test_forward_lookup_bijective(self, store):
        imap, keys = store
        assert imap.size == len(keys)
        seen = set()
        for key in keys:
            idx = imap.get_index(key)
            assert 0 <= idx < imap.size
            seen.add(idx)
        assert len(seen) == len(keys)  # bijection

    def test_contiguous_ordinals_sorted_order(self, store):
        imap, keys = store
        # contiguous ordinals assigned over the sorted key set
        for ordinal, key in enumerate(keys):
            assert imap.get_index(key) == ordinal

    def test_reverse_lookup(self, store):
        imap, keys = store
        for ordinal, key in enumerate(keys):
            assert imap.get_feature_name(ordinal) == key
        assert imap.get_feature_name(imap.size) is None
        assert imap.get_feature_name(-1) is None

    def test_missing_key(self, store):
        imap, _ = store
        assert imap.get_index("no-such-key") == -1
        assert "no-such-key" not in imap
        assert feature_key("f0", "t0") in imap

    def test_reload_from_disk(self, store, tmp_path):
        imap, keys = store
        reloaded = OffHeapIndexMap(imap.directory)
        assert reloaded.size == imap.size
        for key in keys[:50]:
            assert reloaded.get_index(key) == imap.get_index(key)

    def test_batch_lookup(self, store):
        imap, keys = store
        out = imap.get_indices(keys[:100] + ["missing"])
        np.testing.assert_array_equal(out[:100], np.arange(100))
        assert out[100] == -1

    def test_keys_iteration(self, store):
        imap, keys = store
        assert list(imap.keys()) == keys


def test_collision_chains(tmp_path):
    """Keys landing in the same slot must probe correctly (forced via tiny key
    sets whose hashes collide modulo the table size)."""
    builder = OffHeapIndexMapBuilder(str(tmp_path / "c"), num_partitions=1)
    keys = [f"k{i}" for i in range(3)]
    builder.put_all(keys)
    imap = builder.build()
    # table has 16 slots; verify every key still resolves even when slots chain
    for k in sorted(keys):
        assert imap.get_feature_name(imap.get_index(k)) == k


def test_empty_store(tmp_path):
    imap = OffHeapIndexMapBuilder(str(tmp_path / "e"), num_partitions=2).build()
    assert imap.size == 0
    assert imap.get_index("anything") == -1


def test_fnv1a_stable():
    # fixed test vectors (FNV-1a 64 reference values)
    assert _fnv1a(b"") == 0xCBF29CE484222325
    assert _fnv1a(b"a") == 0xAF63DC4C8601EC8C


def test_usable_as_model_io_index_map(tmp_path):
    """OffHeapIndexMap must plug into save_game_model / load_game_model."""
    import jax.numpy as jnp

    from photon_ml_tpu.io.model_io import load_glm_model, save_glm_model
    from photon_ml_tpu.models.glm import Coefficients, LogisticRegressionModel
    from photon_ml_tpu.types import TaskType

    keys = [feature_key(f"f{i}") for i in range(8)]
    imap = OffHeapIndexMapBuilder(str(tmp_path / "im"), num_partitions=2).put_all(keys).build()
    model = LogisticRegressionModel(Coefficients(means=jnp.arange(8, dtype=jnp.float64)))
    save_glm_model(str(tmp_path / "model"), model, imap)
    loaded = load_glm_model(str(tmp_path / "model"), imap)
    np.testing.assert_allclose(
        np.asarray(loaded.coefficients.means), np.arange(8), atol=1e-6
    )
