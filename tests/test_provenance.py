"""photon_ml_tpu.util.provenance — the fields that make recorded baselines
comparable (or visibly incomparable) across commits and machines."""

import json
import multiprocessing
import os
import subprocess

from photon_ml_tpu.util.provenance import measurement_provenance


def _git(tmp, *args):
    subprocess.run(["git", *args], cwd=tmp, check=True, capture_output=True)


def _repo(tmp_path):
    tmp = str(tmp_path)
    _git(tmp, "init", "-q")
    _git(tmp, "config", "user.email", "t@t")
    _git(tmp, "config", "user.name", "t")
    (tmp_path / "f.txt").write_text("x")
    _git(tmp, "add", "-A")
    _git(tmp, "commit", "-qm", "init")
    return tmp


def test_clean_tree_has_plain_commit(tmp_path):
    tmp = _repo(tmp_path)
    p = measurement_provenance(tmp)
    assert p["commit"] and not p["commit"].endswith("-dirty")
    assert p["cpu_count"] == multiprocessing.cpu_count()
    assert p["recorded_at"].endswith("+00:00")


def test_dirty_tree_is_marked(tmp_path):
    tmp = _repo(tmp_path)
    (tmp_path / "f.txt").write_text("changed")
    p = measurement_provenance(tmp)
    assert p["commit"].endswith("-dirty")


def test_recorder_output_file_does_not_count_as_dirt(tmp_path):
    """The recorder rewrites its own output file at recording time; that one
    modification must not stamp every recording -dirty. Regression guard for
    the porcelain leading-space parse (the first line's status space is
    significant and must survive)."""
    tmp = _repo(tmp_path)
    (tmp_path / "baseline.json").write_text("{}")
    _git(tmp, "add", "-A")
    _git(tmp, "commit", "-qm", "baseline")
    (tmp_path / "baseline.json").write_text(json.dumps({"value": 1}))
    assert measurement_provenance(tmp)["commit"].endswith("-dirty")
    p = measurement_provenance(tmp, ignore_paths=("baseline.json",))
    assert not p["commit"].endswith("-dirty")


def test_not_a_repo_gives_null_commit(tmp_path):
    p = measurement_provenance(str(tmp_path))
    assert p["commit"] is None
