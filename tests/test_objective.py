"""GLM objective vs autodiff and vs a naive per-sample reference implementation.

Mirrors the reference's aggregator tests: value/gradient/HVP/Hessian-diag consistency,
normalization algebra identities (margins invariant across spaces), sparse == dense.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu.data.dataset import LabeledData
from photon_ml_tpu.data.matrix import SparseDesignMatrix
from photon_ml_tpu.function.losses import logistic_loss, poisson_loss, squared_loss
from photon_ml_tpu.function.objective import GLMObjective
from photon_ml_tpu.normalization import FeatureDataStatistics, NormalizationContext
from photon_ml_tpu.types import NormalizationType


def make_data(rng, n=50, d=8, with_intercept=True):
    X = rng.normal(size=(n, d))
    if with_intercept:
        X[:, -1] = 1.0
    w_true = rng.normal(size=d)
    z = X @ w_true
    y = (z + 0.3 * rng.normal(size=n) > 0).astype(float)
    offsets = 0.1 * rng.normal(size=n)
    weights = rng.uniform(0.5, 2.0, size=n)
    return LabeledData.build(X, y, offsets, weights), X


@pytest.mark.parametrize("loss", [logistic_loss, squared_loss, poisson_loss], ids=lambda l: l.name)
@pytest.mark.parametrize("l2", [0.0, 0.7])
def test_gradient_matches_autodiff(rng, loss, l2):
    data, _ = make_data(rng)
    obj = GLMObjective(loss)
    coef = jnp.asarray(rng.normal(size=8) * 0.1)
    v, g = obj.value_and_gradient(data, coef, l2)
    v2 = obj.value(data, coef, l2)
    g_auto = jax.grad(lambda c: obj.value(data, c, l2))(coef)
    np.testing.assert_allclose(v, v2, rtol=1e-12)
    np.testing.assert_allclose(g, g_auto, rtol=1e-9, atol=1e-9)


@pytest.mark.parametrize("l2", [0.0, 0.5])
def test_hessian_vector_matches_autodiff(rng, l2):
    data, _ = make_data(rng)
    obj = GLMObjective(logistic_loss)
    coef = jnp.asarray(rng.normal(size=8) * 0.1)
    vec = jnp.asarray(rng.normal(size=8))
    hv = obj.hessian_vector(data, coef, vec, l2)
    grad_fn = lambda c: obj.value_and_gradient(data, c, l2)[1]
    hv_auto = jax.jvp(grad_fn, (coef,), (vec,))[1]
    np.testing.assert_allclose(hv, hv_auto, rtol=1e-8, atol=1e-9)


def test_hessian_diag_and_matrix_consistent(rng):
    data, _ = make_data(rng)
    obj = GLMObjective(logistic_loss)
    coef = jnp.asarray(rng.normal(size=8) * 0.1)
    H = obj.hessian_matrix(data, coef, 0.3)
    diag = obj.hessian_diagonal(data, coef, 0.3)
    np.testing.assert_allclose(jnp.diag(H), diag, rtol=1e-9)
    # H v consistency
    vec = jnp.asarray(rng.normal(size=8))
    np.testing.assert_allclose(H @ vec, obj.hessian_vector(data, coef, vec, 0.3), rtol=1e-8)


@pytest.mark.parametrize(
    "ntype",
    [
        NormalizationType.SCALE_WITH_STANDARD_DEVIATION,
        NormalizationType.SCALE_WITH_MAX_MAGNITUDE,
        NormalizationType.STANDARDIZATION,
    ],
)
def test_normalized_objective_equals_materialized(rng, ntype):
    """Folded normalization == explicitly normalizing the data (the aggregator algebra)."""
    data, X = make_data(rng)
    d = X.shape[1]
    stats = FeatureDataStatistics.compute(X, intercept_index=d - 1)
    norm = NormalizationContext.build(ntype, stats)
    obj_folded = GLMObjective(logistic_loss, norm)

    Xn = np.array(X)
    if norm.shifts is not None:
        Xn = Xn - norm.shifts[None, :]
    if norm.factors is not None:
        Xn = Xn * norm.factors[None, :]
    data_mat = LabeledData.build(Xn, data.labels, data.offsets, data.weights)
    obj_plain = GLMObjective(logistic_loss)

    coef = jnp.asarray(rng.normal(size=d) * 0.2)
    v1, g1 = obj_folded.value_and_gradient(data, coef, 0.1)
    v2, g2 = obj_plain.value_and_gradient(data_mat, coef, 0.1)
    np.testing.assert_allclose(v1, v2, rtol=1e-9)
    np.testing.assert_allclose(g1, g2, rtol=1e-8, atol=1e-9)

    vec = jnp.asarray(rng.normal(size=d))
    np.testing.assert_allclose(
        obj_folded.hessian_vector(data, coef, vec, 0.1),
        obj_plain.hessian_vector(data_mat, coef, vec, 0.1),
        rtol=1e-8, atol=1e-9,
    )
    np.testing.assert_allclose(
        obj_folded.hessian_diagonal(data, coef, 0.1),
        obj_plain.hessian_diagonal(data_mat, coef, 0.1),
        rtol=1e-8, atol=1e-9,
    )


def test_coefficient_space_roundtrip(rng):
    X = rng.normal(size=(40, 6))
    X[:, -1] = 1.0
    stats = FeatureDataStatistics.compute(X, intercept_index=5)
    norm = NormalizationContext.build(NormalizationType.STANDARDIZATION, stats)
    w = rng.normal(size=6)
    back = norm.model_to_transformed_space(norm.model_to_original_space(w))
    np.testing.assert_allclose(back, w, rtol=1e-12)
    # margin invariance: w'.x' == w.x for w = to_original(w')
    w_orig = norm.model_to_original_space(w)
    Xn = (X - norm.shifts[None, :]) * norm.factors[None, :]
    np.testing.assert_allclose(Xn @ w, X @ w_orig, rtol=1e-9)


def test_sparse_matches_dense(rng):
    Xd = rng.normal(size=(30, 12)) * (rng.uniform(size=(30, 12)) < 0.3)
    y = jnp.asarray((rng.uniform(size=30) > 0.5).astype(float))
    w8 = rng.uniform(0.5, 1.5, size=30)
    dense = LabeledData.build(Xd, y, weights=w8)
    Xs = SparseDesignMatrix.from_scipy(sp.csr_matrix(Xd), dtype=jnp.float64, pad_nnz=400)
    sparse = LabeledData.build(Xs, y, weights=w8)
    obj_d = GLMObjective(logistic_loss)
    coef = jnp.asarray(rng.normal(size=12) * 0.3)
    vd, gd = obj_d.value_and_gradient(dense, coef, 0.2)
    vs, gs = obj_d.value_and_gradient(sparse, coef, 0.2)
    np.testing.assert_allclose(vd, vs, rtol=1e-10)
    np.testing.assert_allclose(gd, gs, rtol=1e-9, atol=1e-10)
    vec = jnp.asarray(rng.normal(size=12))
    np.testing.assert_allclose(
        obj_d.hessian_vector(dense, coef, vec),
        obj_d.hessian_vector(sparse, coef, vec),
        rtol=1e-9, atol=1e-10,
    )
    np.testing.assert_allclose(
        obj_d.hessian_diagonal(dense, coef),
        obj_d.hessian_diagonal(sparse, coef),
        rtol=1e-9, atol=1e-10,
    )


def test_padded_rows_are_inert(rng):
    """Padding rows with weight 0 and zero features must not change anything."""
    data, X = make_data(rng, n=20)
    Xp = np.vstack([X, np.zeros((5, 8))])
    yp = np.concatenate([np.asarray(data.labels), np.zeros(5)])
    op = np.concatenate([np.asarray(data.offsets), np.zeros(5)])
    wp = np.concatenate([np.asarray(data.weights), np.zeros(5)])
    padded = LabeledData.build(Xp, yp, op, wp)
    obj = GLMObjective(poisson_loss)
    coef = jnp.asarray(rng.normal(size=8) * 0.1)
    v1, g1 = obj.value_and_gradient(data, coef, 0.1)
    v2, g2 = obj.value_and_gradient(padded, coef, 0.1)
    np.testing.assert_allclose(v1, v2, rtol=1e-12)
    np.testing.assert_allclose(g1, g2, rtol=1e-12)


def test_sparse_feature_statistics_match_dense():
    """Sparse FeatureDataStatistics must equal the dense computation, including
    implicit-zero min/max handling and empty columns."""
    import scipy.sparse as sp

    rng = np.random.default_rng(17)
    n, d = 60, 9
    X = rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.3)
    X[:, 3] = 0.0  # empty column
    X[:, 4] = 2.0  # fully dense positive column (no implicit zero)
    dense = FeatureDataStatistics.compute(X, intercept_index=4)
    sparse = FeatureDataStatistics.compute(sp.csr_matrix(X), intercept_index=4)
    for field in ("mean", "variance", "min", "max", "num_nonzeros", "mean_abs"):
        np.testing.assert_allclose(
            getattr(sparse, field), getattr(dense, field), atol=1e-12, err_msg=field
        )
    assert sparse.count == dense.count == n
    assert sparse.min[4] == 2.0  # fully dense column keeps its true min (not 0)


def test_weight_zero_rows_never_poison_even_when_loss_overflows(rng):
    """A weight-0 row whose margin overflows the pointwise loss (exp in Poisson
    at f32) must be EXCLUDED, not multiplied (0 * inf = NaN): weight-0 rows are
    routine — down-sampled negatives, padded entity buckets, weight-masked
    learning-curve subsets (diagnostics/fitting.py)."""
    import jax.numpy as jnp

    from photon_ml_tpu.data.dataset import LabeledData
    from photon_ml_tpu.function.losses import poisson_loss
    from photon_ml_tpu.function.objective import GLMObjective

    X = np.asarray([[1.0], [200.0]])  # second row: exp(200) overflows even f64
    y = np.asarray([1.0, 1.0])
    w = np.asarray([0.0, 1.0])  # overflowing row carries weight 0
    data = LabeledData.build(X, y, weights=w, dtype=jnp.float64)
    obj = GLMObjective(poisson_loss)
    coef = jnp.asarray([1.0], dtype=jnp.float64)
    value, grad = obj.value_and_gradient(data, coef)
    assert np.isfinite(float(value))
    assert np.all(np.isfinite(np.asarray(grad)))
    hv = obj.hessian_vector(data, coef, jnp.asarray([1.0], dtype=jnp.float64))
    assert np.all(np.isfinite(np.asarray(hv)))
    assert np.all(np.isfinite(np.asarray(obj.hessian_diagonal(data, coef))))


def test_bf16_feature_storage_matches_f32_loosely(rng):
    """bf16-stored dense design matrices (DenseDesignMatrix._mxu_dot: half the
    HBM bytes, f32 accumulation) agree with f32 storage to bf16 rounding, and
    always return the compute dtype."""
    import jax.numpy as jnp

    from photon_ml_tpu.data.matrix import DenseDesignMatrix

    X = rng.normal(size=(64, 16)).astype(np.float32)
    w = jnp.asarray(rng.normal(size=16).astype(np.float32))
    v = jnp.asarray(rng.normal(size=64).astype(np.float32))
    m32 = DenseDesignMatrix(values=jnp.asarray(X))
    mbf = DenseDesignMatrix(values=jnp.asarray(X, dtype=jnp.bfloat16))
    assert mbf.matvec(w).dtype == w.dtype
    assert mbf.rmatvec(v).dtype == v.dtype
    np.testing.assert_allclose(
        np.asarray(mbf.matvec(w)), np.asarray(m32.matvec(w)), rtol=0, atol=0.05
    )
    np.testing.assert_allclose(
        np.asarray(mbf.rmatvec(v)), np.asarray(m32.rmatvec(v)), rtol=0, atol=0.1
    )
    np.testing.assert_allclose(
        np.asarray(mbf.row_sq_dot(w)), np.asarray(m32.row_sq_dot(w)), rtol=0.02, atol=0.05
    )


def test_sparse_sorted_col_reduce_matches_scatter(rng, monkeypatch):
    """The TPU-side sorted segment_sum column reduction (data/matrix.py
    COL_REDUCE_MODE) produces the same rmatvec as the CPU scatter-add path."""
    import scipy.sparse as sp

    from photon_ml_tpu.data import matrix as matrix_mod
    from photon_ml_tpu.data.matrix import SparseDesignMatrix

    X = sp.random(300, 50, density=0.1, random_state=np.random.RandomState(3))
    # build under "sorted" so from_scipy materializes the sorted-layout
    # metadata (on the CPU backend "auto" skips it to save the sort)
    monkeypatch.setattr(matrix_mod, "COL_REDUCE_MODE", "sorted")
    m = SparseDesignMatrix.from_scipy(X.tocsr(), dtype=jnp.float64)
    assert m.col_order is not None
    v = jnp.asarray(rng.normal(size=300))
    sorted_ = np.asarray(m.rmatvec(v))
    monkeypatch.setattr(matrix_mod, "COL_REDUCE_MODE", "scatter")
    scatter = np.asarray(m.rmatvec(v))
    np.testing.assert_allclose(sorted_, scatter, rtol=1e-12)
    np.testing.assert_allclose(scatter, np.asarray(X.T @ np.asarray(v)), rtol=1e-9)
    # sharded construction leaves the metadata off -> scatter path regardless
    import dataclasses as dc

    bare = dc.replace(m, col_order=None, cols_sorted=None)
    np.testing.assert_allclose(np.asarray(bare.rmatvec(v)), scatter, rtol=1e-12)
