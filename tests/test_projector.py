"""Projector tests: Gaussian JL matrix, margin invariance of back-projection,
dataset building under RANDOM_PROJECTION, end-to-end estimator fit + transform,
and save/load through name space. Mirrors the reference's projector integ tests
(photon-api src/integTest projector/ — ProjectionMatrixIntegTest,
IndexMapProjectorRDDIntegTest semantics).
"""

import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu.data.game_data import GameInput
from photon_ml_tpu.data.projector import (
    ProjectorConfig,
    ProjectorType,
    RandomProjector,
    build_gaussian_projection_matrix,
    make_projector,
)
from photon_ml_tpu.data.random_effect import build_random_effect_dataset
from photon_ml_tpu.estimators.config import (
    CoordinateConfiguration,
    FixedEffectDataConfiguration,
    RandomEffectDataConfiguration,
)
from photon_ml_tpu.estimators.game_estimator import GameEstimator
from photon_ml_tpu.optimization.common import OptimizerConfig
from photon_ml_tpu.optimization.config import (
    GLMOptimizationConfiguration,
    RegularizationContext,
)
from photon_ml_tpu.transformers.game_transformer import GameTransformer
from photon_ml_tpu.types import OptimizerType, RegularizationType, TaskType

OPT = GLMOptimizationConfiguration(
    optimizer_config=OptimizerConfig(optimizer_type=OptimizerType.LBFGS, max_iterations=60),
    regularization_context=RegularizationContext(RegularizationType.L2),
    regularization_weight=0.5,
)


def test_gaussian_matrix_is_deterministic_and_scaled():
    P1 = build_gaussian_projection_matrix(200, 20, seed=7)
    P2 = build_gaussian_projection_matrix(200, 20, seed=7)
    P3 = build_gaussian_projection_matrix(200, 20, seed=8)
    assert np.array_equal(P1, P2)
    assert not np.array_equal(P1, P3)
    # N(0, 1/k) entries: projected squared norms are unbiased estimates
    rng = np.random.default_rng(0)
    x = rng.normal(size=200)
    projected = x @ P1
    assert np.linalg.norm(projected) ** 2 == pytest.approx(
        np.linalg.norm(x) ** 2, rel=0.5
    )


def test_projector_config_validation():
    with pytest.raises(ValueError):
        ProjectorConfig(ProjectorType.RANDOM_PROJECTION)
    assert make_projector(ProjectorConfig(), 10) is None
    assert make_projector(ProjectorConfig(ProjectorType.IDENTITY_PROJECTION), 10) is None
    proj = make_projector(
        ProjectorConfig(ProjectorType.RANDOM_PROJECTION, projected_dim=4), 10
    )
    assert proj.matrix.shape == (10, 4)
    assert proj.projected_dim == 4


def test_back_projection_margin_invariance():
    """x_proj . w == x . (P w): back-projected coefficients reproduce projected
    margins exactly (the identity RandomEffectModelInProjectedSpace relies on)."""
    rng = np.random.default_rng(3)
    d, k, n = 30, 6, 50
    proj = RandomProjector(matrix=build_gaussian_projection_matrix(d, k, 1))
    X = sp.csr_matrix(rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.3))
    Xp = np.asarray(proj.project_features(X).todense())
    w_proj = rng.normal(size=k)
    margins_projected = Xp @ w_proj
    margins_back = X @ proj.project_coefficients_back(w_proj)
    np.testing.assert_allclose(margins_back, margins_projected, rtol=1e-10)


def test_intercept_passthrough():
    rng = np.random.default_rng(4)
    d, k, n = 12, 3, 20
    icept = 5
    proj = RandomProjector(
        matrix=build_gaussian_projection_matrix(d, k, 2), intercept_index=icept
    )
    X = np.zeros((n, d))
    X[:, icept] = 1.0  # intercept column
    X[:, 0] = rng.normal(size=n)
    Xp = np.asarray(proj.project_features(sp.csr_matrix(X)).todense())
    assert proj.projected_dim == k + 1
    # last projected column IS the intercept, untouched
    np.testing.assert_allclose(Xp[:, -1], 1.0)
    # margins invariant including the intercept slot
    w_proj = rng.normal(size=k + 1)
    np.testing.assert_allclose(
        X @ proj.project_coefficients_back(w_proj), Xp @ w_proj, rtol=1e-10
    )


def test_normalization_folding():
    """A projector carrying a NormalizationContext projects normalize(X), and
    project_coefficients_back un-does the normalization (margin invariance over
    RAW features)."""
    from photon_ml_tpu.normalization import NormalizationContext

    rng = np.random.default_rng(5)
    d, k, n = 10, 4, 25
    icept = 0
    X = rng.normal(size=(n, d)) + 2.0
    X[:, icept] = 1.0
    factors = rng.random(d) + 0.5
    shifts = rng.normal(size=d)
    factors[icept], shifts[icept] = 1.0, 0.0
    norm = NormalizationContext(factors=factors, shifts=shifts, intercept_index=icept)
    P = build_gaussian_projection_matrix(d, k, 3)
    proj_n = RandomProjector(matrix=P, intercept_index=icept, normalization=norm)
    proj_raw = RandomProjector(matrix=P, intercept_index=icept)
    folded = np.asarray(proj_n.project_features(sp.csr_matrix(X)).todense())
    explicit = np.asarray(
        proj_raw.project_features(sp.csr_matrix((X - shifts) * factors)).todense()
    )
    np.testing.assert_allclose(folded, explicit, rtol=1e-9, atol=1e-12)
    # back-projection: margins over RAW features == margins in normalized-projected
    # space (the property training/scoring/export consistency rests on)
    w_proj = rng.normal(size=k + 1)
    w_orig = proj_n.project_coefficients_back(w_proj)
    np.testing.assert_allclose(X @ w_orig, folded @ w_proj, rtol=1e-9)
    # batched == per-row
    W = rng.normal(size=(3, k + 1))
    batched = proj_n.project_coefficients_back(W)
    for i in range(3):
        np.testing.assert_allclose(
            batched[i], proj_n.project_coefficients_back(W[i]), rtol=1e-12
        )


def test_original_space_model_refuses_projected_dataset():
    """Silent misalignment guard: an original-space model cannot score a
    projected dataset (no exact original->projected transport)."""
    rng = np.random.default_rng(9)
    n, d, k = 60, 20, 4
    ents = rng.integers(0, 3, size=n)
    X = sp.csr_matrix(rng.normal(size=(n, d)))
    y = (rng.random(n) > 0.5).astype(np.float64)
    proj = make_projector(
        ProjectorConfig(ProjectorType.RANDOM_PROJECTION, projected_dim=k, seed=4), d
    )
    ds_proj = build_random_effect_dataset(X, ents, "e", labels=y, projector=proj)
    ds_orig = build_random_effect_dataset(X, ents, "e", labels=y)
    from photon_ml_tpu.algorithm.coordinate import RandomEffectCoordinate
    import jax.numpy as jnp

    coord = RandomEffectCoordinate(
        coordinate_id="e", dataset=ds_orig, task=TaskType.LOGISTIC_REGRESSION,
        configuration=OPT, base_offsets=jnp.zeros(n),
    )
    model_orig = coord.initialize_model()
    with pytest.raises(ValueError, match="original-space"):
        model_orig.score_dataset(ds_proj)


def test_different_projectors_refused():
    """Two different random projections must not silently score each other."""
    rng = np.random.default_rng(11)
    n, d, k = 40, 15, 4
    ents = rng.integers(0, 3, size=n)
    X = sp.csr_matrix(rng.normal(size=(n, d)))
    y = (rng.random(n) > 0.5).astype(np.float64)
    cfg = dict(projected_dim=k)
    p1 = make_projector(ProjectorConfig(ProjectorType.RANDOM_PROJECTION, seed=1, **cfg), d)
    p2 = make_projector(ProjectorConfig(ProjectorType.RANDOM_PROJECTION, seed=2, **cfg), d)
    ds1 = build_random_effect_dataset(X, ents, "e", labels=y, projector=p1)
    ds2 = build_random_effect_dataset(X, ents, "e", labels=y, projector=p2)
    from photon_ml_tpu.algorithm.coordinate import RandomEffectCoordinate
    import jax.numpy as jnp

    coord = RandomEffectCoordinate(
        coordinate_id="e", dataset=ds1, task=TaskType.LOGISTIC_REGRESSION,
        configuration=OPT, base_offsets=jnp.zeros(n),
    )
    model = coord.initialize_model()
    assert np.all(np.isfinite(np.asarray(model.score_dataset(ds1))))  # same proj ok
    with pytest.raises(ValueError, match="different RandomProjectors"):
        model.score_dataset(ds2)


def test_normalized_projection_scoring_consistency():
    """Training under normalization + RANDOM_PROJECTION must score raw
    validation features correctly (regression test: the projector carries the
    normalization so scoring datasets fold it too)."""
    from photon_ml_tpu.normalization import FeatureDataStatistics, NormalizationContext
    from photon_ml_tpu.types import NormalizationType

    rng = np.random.default_rng(10)
    data = _glmix_input(rng, n=500, d=30, n_users=6)
    # shift/scale the per-user shard so normalization is material
    per_user = data.features["per-user"].toarray()
    per_user[:, 1:] = per_user[:, 1:] * 5.0 + 1.0 * (per_user[:, 1:] != 0)
    data = GameInput(
        features={"global": data.features["global"], "per-user": sp.csr_matrix(per_user)},
        labels=data.labels,
        id_columns=data.id_columns,
    )
    stats = FeatureDataStatistics.compute(per_user, intercept_index=0)
    norm = NormalizationContext.build(NormalizationType.STANDARDIZATION, stats)
    configs = {
        "per-user": CoordinateConfiguration(
            data_config=RandomEffectDataConfiguration(
                "userId", "per-user",
                projector=ProjectorConfig(
                    ProjectorType.RANDOM_PROJECTION, projected_dim=10, seed=5,
                    intercept_index=0,
                ),
            ),
            optimization_config=OPT,
        ),
    }
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinate_configurations=configs,
        normalization_contexts={"per-user": norm},
    )
    model = est.fit(data)[0].model
    re_model = model.get_model("per-user")
    assert re_model.projector is not None and re_model.projector.normalization is not None
    # transform scores (raw features in, projector folds normalization)
    scores = GameTransformer(model=model).score(data, include_offsets=False)
    pos, neg = scores[data.labels == 1], scores[data.labels == 0]
    assert (pos[:, None] > neg[None, :]).mean() > 0.7
    # export path: back-projected original-space model reproduces the scores
    # over RAW features
    back = re_model.to_original_space()
    scores_back = GameTransformer(
        model=model.update_model("per-user", back)
    ).score(data, include_offsets=False)
    np.testing.assert_allclose(scores_back, scores, rtol=1e-3, atol=1e-4)


def test_dataset_built_in_projected_space():
    rng = np.random.default_rng(6)
    n, d, k = 120, 40, 8
    ents = rng.integers(0, 6, size=n)
    X = sp.csr_matrix(rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.25))
    y = (rng.random(n) > 0.5).astype(np.float64)
    proj = make_projector(
        ProjectorConfig(ProjectorType.RANDOM_PROJECTION, projected_dim=k, seed=1), d
    )
    ds = build_random_effect_dataset(
        X, ents, "e", labels=y, projector=proj
    )
    # every entity observes all k projected columns
    assert ds.max_k == k
    assert ds.projector is proj
    pt = np.asarray(ds.proj_indices)
    for row in pt:
        np.testing.assert_array_equal(np.sort(row[row >= 0]), np.arange(k))


def _glmix_input(rng, n=600, d=40, n_users=7):
    w = rng.normal(size=d) * 0.6
    bias = rng.normal(size=n_users) * 1.2
    X = (rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.4)).astype(np.float64)
    # deterministic round-robin entities: stable bucket shapes -> shared compiles
    users = np.arange(n) % n_users
    z = X @ w + bias[users]
    y = (z + 0.2 * rng.normal(size=n) > 0).astype(np.float64)
    uid = np.asarray([f"u{u}" for u in users], dtype=object)
    # per-user shard: intercept + the global features (high-dim, worth projecting)
    per_user = sp.hstack([sp.csr_matrix(np.ones((n, 1))), sp.csr_matrix(X)]).tocsr()
    return GameInput(
        features={"global": X, "per-user": per_user},
        labels=y,
        id_columns={"userId": uid},
    )


def test_estimator_end_to_end_with_random_projection():
    rng = np.random.default_rng(7)
    data = _glmix_input(rng)
    configs = {
        "fixed": CoordinateConfiguration(
            data_config=FixedEffectDataConfiguration("global"),
            optimization_config=OPT,
        ),
        "per-user": CoordinateConfiguration(
            data_config=RandomEffectDataConfiguration(
                "userId",
                "per-user",
                projector=ProjectorConfig(
                    ProjectorType.RANDOM_PROJECTION, projected_dim=8, seed=2,
                    intercept_index=0,
                ),
            ),
            optimization_config=OPT,
        ),
    }
    est = GameEstimator(task=TaskType.LOGISTIC_REGRESSION, coordinate_configurations=configs)
    result = est.fit(data)[0]
    model = result.model
    re_model = model.get_model("per-user")
    assert re_model.projector is not None
    # 9 valid slots (8 projected + intercept); width may be pow2-padded beyond that
    proj = np.asarray(re_model.proj_indices)
    assert int((proj[0] >= 0).sum()) == 9

    # transform end-to-end: model carries the projector, scores are finite and
    # discriminative (AUC over train data comfortably above chance)
    scores = GameTransformer(model=model).score(data, include_offsets=False)
    assert np.all(np.isfinite(scores))
    pos, neg = scores[data.labels == 1], scores[data.labels == 0]
    auc = (pos[:, None] > neg[None, :]).mean()
    assert auc > 0.75

    # back-projection to original space preserves the model's scores
    back = re_model.to_original_space()
    assert back.projector is None
    game2 = model.update_model("per-user", back)
    scores2 = GameTransformer(model=game2).score(data, include_offsets=False)
    # f32 round-off: back-projection reorders the accumulation
    np.testing.assert_allclose(scores2, scores, rtol=1e-4, atol=1e-6)


def test_projected_model_save_load_roundtrip(tmp_path):
    from photon_ml_tpu.data.index_map import IndexMap
    from photon_ml_tpu.io.model_io import load_game_model, save_game_model

    rng = np.random.default_rng(8)
    data = _glmix_input(rng, n=300, d=20, n_users=4)
    configs = {
        "per-user": CoordinateConfiguration(
            data_config=RandomEffectDataConfiguration(
                "userId",
                "per-user",
                projector=ProjectorConfig(
                    ProjectorType.RANDOM_PROJECTION, projected_dim=6, seed=3,
                    intercept_index=0,
                ),
            ),
            optimization_config=OPT,
        ),
    }
    est = GameEstimator(task=TaskType.LOGISTIC_REGRESSION, coordinate_configurations=configs)
    model = est.fit(data)[0].model
    index_maps = {"per-user": IndexMap([f"f{i}\x01" for i in range(21)])}
    out = str(tmp_path / "model")
    save_game_model(out, model, index_maps)
    loaded = load_game_model(out, index_maps)
    scores = GameTransformer(model=model).score(data, include_offsets=False)
    scores_loaded = GameTransformer(model=loaded).score(data, include_offsets=False)
    np.testing.assert_allclose(scores_loaded, scores, rtol=1e-4, atol=1e-6)
