"""Util subsystem tests: PhotonLogger file output + level filter, Timed sections,
EventEmitter dispatch (photon-lib util/PhotonLogger, util/Timed, client event/*)."""

import logging

from photon_ml_tpu.util import Event, EventEmitter, EventListener, PhotonLogger, Timed, timed


def test_photon_logger_writes_file_with_level_filter(tmp_path):
    path = tmp_path / "run.log"
    with PhotonLogger(str(path), level="WARN", echo=False) as log:
        log.debug("hidden-debug")
        log.info("hidden-info")
        log.warning("shown-warning")
        log.error("shown-error")
    text = path.read_text()
    assert "shown-warning" in text and "shown-error" in text
    assert "hidden-debug" not in text and "hidden-info" not in text


def test_photon_logger_set_level(tmp_path):
    path = tmp_path / "run.log"
    with PhotonLogger(str(path), level="ERROR", echo=False) as log:
        log.info("first-hidden")
        log.set_level("DEBUG")
        log.debug("now-shown")
    text = path.read_text()
    assert "first-hidden" not in text and "now-shown" in text


def test_timed_records_elapsed(tmp_path):
    with Timed("phase") as t:
        sum(range(1000))
    assert t.seconds is not None and t.seconds >= 0


def test_timed_decorator_logs(caplog):
    @timed("compute", logger=logging.getLogger("photon.timed"))
    def fn():
        return 42

    with caplog.at_level(logging.INFO, logger="photon.timed"):
        assert fn() == 42
    assert any("compute took" in r.message for r in caplog.records)


def test_event_emitter_dispatch_and_clear():
    seen = []

    class Collector(EventListener):
        def on_event(self, event):
            seen.append(event.name)

    emitter = EventEmitter()
    emitter.register_listener(Collector())
    emitter.send_event(Event("TrainingStartEvent"))
    emitter.send_event(Event("TrainingFinishEvent", {"k": 1}))
    assert seen == ["TrainingStartEvent", "TrainingFinishEvent"]
    emitter.clear_listeners()
    emitter.send_event(Event("IgnoredEvent"))
    assert len(seen) == 2


def test_event_emitter_class_path_registration():
    import importlib

    emitter = EventEmitter()
    emitter.register_listener_class("tests.test_util.RecordingListener")
    emitter.send_event(Event("PhotonSetupEvent"))
    # the dotted path may resolve to a distinct module object under pytest's
    # import scheme; assert against the class the emitter actually instantiated
    cls = importlib.import_module("tests.test_util").RecordingListener
    assert cls.events == ["PhotonSetupEvent"]


class RecordingListener(EventListener):
    events: list = []

    def on_event(self, event):
        RecordingListener.events.append(event.name)
