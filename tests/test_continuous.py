"""Continuous-training subsystem tests (photon_ml_tpu/continuous/).

The three layers and the closed loop:

- stable index-map growth (`IndexMap.extend`): old (key -> index) pairs are
  bitwise-frozen across growth — the alignment-by-construction contract every
  previous-generation coefficient table leans on;
- the append-only corpus manifest: scan diffs ARE the delta, contract
  violations (rewritten/vanished part files) fail loudly;
- delta-only ingest: re-ingesting the whole manifest with the final frozen
  maps reproduces the progressively accumulated corpus bit for bit;
- active-set selection (new-data / new-entity / gradient-screen rules) and
  the fixed-effect refresh reservoir;
- the `ContinuousTrainer` generation loop end to end: bootstrap + delta
  generations, untouched entities bitwise-stable across generations,
  restart-resume from the committed state, and the committed delta
  generation hot-swapping into PR 6's live serving frontend mid-traffic;
- the `continuous.*` chaos sweep: crash at every fault point mid-delta,
  restart, and the exported generation bytes match an uninterrupted run's.
"""

import dataclasses
import hashlib
import os
import shutil
import time
from types import SimpleNamespace

import numpy as np
import pytest

from photon_ml_tpu.cli.parsers import (
    parse_coordinate_configuration,
    parse_feature_shard_configuration,
)
from photon_ml_tpu.continuous import (
    ContinuousTrainer,
    ContinuousTrainerConfig,
    CorpusContractViolation,
    CorpusManifest,
    ReservoirDownSampler,
    ingest_delta,
    select_active_entities,
)
from photon_ml_tpu.data import avro_io
from photon_ml_tpu.data.index_map import IndexMap
from photon_ml_tpu.data.readers import read_merged_avro
from photon_ml_tpu.io.checkpoint import list_generations, load_generation
from photon_ml_tpu.resilience import (
    InjectedFault,
    armed,
    assert_trees_identical,
    registered_fault_points,
    run_with_crash_at,
)
from photon_ml_tpu.types import TaskType

D = 3
USERS = [f"u{i}" for i in range(8)]
_rng0 = np.random.default_rng(0)
W_TRUE = _rng0.normal(size=D)
BIAS = dict(zip(USERS, _rng0.normal(size=len(USERS)) * 1.5))
BIAS["a-new"] = 1.0  # sorts BEFORE u*: must still append at the entity tail

FE_COORD = (
    "name=global,feature.shard=shardA,optimizer=LBFGS,"
    "max.iter=25,tolerance=1e-7,regularization=L2,reg.weights=1.0"
)
RE_COORD = (
    "name=per-user,random.effect.type=userId,feature.shard=shardA,"
    "optimizer=LBFGS,max.iter=25,tolerance=1e-7,regularization=L2,"
    "reg.weights=1.0"
)
SHARD = "name=shardA,feature.bags=features"


def write_part(path, rng, n, user_labels, extra_feature=None):
    """One TrainingExampleAvro part file over the shared ground truth; rows
    draw entities from ``user_labels`` only (the delta-targeting knob)."""
    X = rng.normal(size=(n, D))
    us = [user_labels[i] for i in rng.integers(0, len(user_labels), size=n)]
    z = X @ W_TRUE + np.array([BIAS[u] for u in us])
    y = (z + 0.3 * rng.normal(size=n) > 0).astype(np.float64)

    def records():
        base = os.path.basename(str(path))
        for i in range(n):
            feats = [
                {"name": f"f{j}", "term": "", "value": float(X[i, j])}
                for j in range(D)
            ]
            if extra_feature is not None:
                feats.append({"name": extra_feature, "term": "", "value": 1.0})
            yield {
                "uid": f"{base}#{i}",
                "label": float(y[i]),
                "features": feats,
                "metadataMap": {"userId": us[i]},
                "weight": 1.0,
                "offset": 0.0,
            }

    avro_io.write_container(
        str(path), avro_io.TRAINING_EXAMPLE_SCHEMA, records()
    )


def shard_configs():
    return dict([parse_feature_shard_configuration(SHARD)])


def make_trainer(corpus, ckpt, export_dir=None, gradient_threshold=None,
                 fe_reservoir=None, iterations=1, mesh=None, **kwargs):
    coords = dict(
        parse_coordinate_configuration(c) for c in (FE_COORD, RE_COORD)
    )
    return ContinuousTrainer(
        ContinuousTrainerConfig(
            corpus_paths=[str(corpus)],
            checkpoint_directory=str(ckpt),
            task=TaskType.LOGISTIC_REGRESSION,
            coordinate_configurations=coords,
            shard_configurations=shard_configs(),
            delta_iterations=iterations,
            initial_iterations=iterations,
            gradient_threshold=gradient_threshold,
            fe_reservoir=fe_reservoir,
            export_directory=None if export_dir is None else str(export_dir),
            mesh=mesh,
            **kwargs,
        )
    )


# ------------------------------------------------------- stable index growth


class TestIndexMapExtend:
    def test_existing_pairs_are_frozen_across_growth(self):
        base = IndexMap.build(["b", "d", "a", "c"], add_intercept=False)
        before = {k: base.get_index(k) for k in base.keys()}
        grown = base.extend(["z", "a", "e", "c", "x"])
        # regression: every old name -> index pair is bitwise-stable
        for k, i in before.items():
            assert grown.get_index(k) == i
        # unseen keys append at the tail in sorted order
        assert grown.keys() == base.keys() + ["e", "x", "z"]
        assert grown.size == base.size + 3

    def test_noop_extend_returns_self(self):
        base = IndexMap.build(["a", "b"], add_intercept=False)
        assert base.extend(["b", "a"]) is base
        assert base.extend([]) is base

    def test_indices_never_move_across_repeated_shuffled_growth(self):
        rng = np.random.default_rng(1)
        m = IndexMap.build([f"k{i}" for i in range(5)], add_intercept=False)
        assigned = {k: m.get_index(k) for k in m.keys()}
        for round_ in range(4):
            new = [f"g{round_}-{j}" for j in range(3)]
            observed = list(assigned) + new
            rng.shuffle(observed)  # observation order must not matter
            m = m.extend(observed)
            for k, i in assigned.items():
                assert m.get_index(k) == i
            for k in new:
                assigned[k] = m.get_index(k)
                assert assigned[k] >= 0

    def test_intercept_index_survives_growth(self):
        base = IndexMap.build(["f0", "f1"], add_intercept=True)
        grown = base.extend(["f2"])
        assert grown.intercept_index == base.intercept_index


# ------------------------------------------------------------ corpus manifest


def _touch(path, payload):
    with open(path, "wb") as f:
        f.write(payload)


class TestCorpusManifest:
    def test_scan_extend_diff_cycle(self, tmp_path):
        a, b = str(tmp_path / "part-a.avro"), str(tmp_path / "part-b.avro")
        _touch(a, b"aaaa")
        _touch(b, b"bbbbbb")
        m = CorpusManifest()
        assert m.scan([str(tmp_path)]) == [a, b]  # listing order
        m = m.extend([a])
        assert m.scan([str(tmp_path)]) == [b]
        m = m.extend([b])
        assert m.scan([str(tmp_path)]) == []
        assert m.paths == (a, b)
        assert [e.size for e in m.entries] == [4, 6]
        assert m.entries[0].sha256 == hashlib.sha256(b"aaaa").hexdigest()

    def test_round_trip_through_checkpoint_dict(self, tmp_path):
        a = str(tmp_path / "part-a.avro")
        _touch(a, b"payload")
        m = CorpusManifest().extend([a])
        again = CorpusManifest.from_dict(m.to_dict())
        assert again == m
        assert again.scan([str(tmp_path)]) == []

    def test_rewritten_part_file_violates_the_contract(self, tmp_path):
        a = str(tmp_path / "part-a.avro")
        _touch(a, b"original")
        m = CorpusManifest().extend([a])
        _touch(a, b"rewritten-longer")
        with pytest.raises(CorpusContractViolation, match="changed size"):
            m.scan([str(tmp_path)])

    def test_vanished_part_file_violates_the_contract(self, tmp_path):
        a = str(tmp_path / "part-a.avro")
        _touch(a, b"here")
        m = CorpusManifest().extend([a])
        os.remove(a)
        with pytest.raises(CorpusContractViolation, match="disappeared"):
            m.scan([str(tmp_path)])

    def test_same_size_rewrite_fails_fingerprint_verification(self, tmp_path):
        # scan's per-poll check is size-only (cheap); the persisted sha256 is
        # enforced at restart, where a same-size rewrite must fail loudly
        a = str(tmp_path / "part-a.avro")
        _touch(a, b"original")
        m = CorpusManifest().extend([a])
        m.verify_fingerprints()
        _touch(a, b"RIGWRITE")  # same 8 bytes, different content
        assert m.scan([str(tmp_path)]) == []  # the cheap check cannot see it
        with pytest.raises(CorpusContractViolation, match="content changed"):
            m.verify_fingerprints()

    def test_file_grown_during_ingest_fails_verify_sizes(self, tmp_path):
        # the torn-write bracket: extend() records the size BEFORE the decode,
        # verify_sizes() after — a file an upstream writer was still appending
        # to fails loudly instead of leaving a manifest record that disagrees
        # with the rows the model absorbed
        a = str(tmp_path / "part-a.avro")
        _touch(a, b"prefix")
        m = CorpusManifest().extend([a])
        m.verify_sizes()  # quiescent corpus passes
        with open(a, "ab") as f:
            f.write(b"-late-append")
        with pytest.raises(CorpusContractViolation, match="during ingest"):
            m.verify_sizes(m.entries[-1:])


# -------------------------------------------------------------- delta ingest


def _csr_state(m):
    c = m.tocsr()
    return c.indptr, c.indices, c.data


class TestIngestDelta:
    @pytest.fixture()
    def parts(self, tmp_path):
        rng = np.random.default_rng(2)
        p0 = tmp_path / "part-00000.avro"
        p1 = tmp_path / "part-00001.avro"
        write_part(p0, rng, 60, USERS)
        # the delta brings a NEW entity and a NEW feature
        write_part(p1, rng, 20, ["u0", "a-new"], extra_feature="f-late")
        return str(p0), str(p1)

    def test_delta_grows_without_disturbing_old_state(self, parts):
        p0, p1 = parts
        snap0, info0 = ingest_delta(None, [p0], shard_configs(), ("userId",))
        assert info0.row_start == 0 and info0.n_new_rows == snap0.n_rows
        snap1, info1 = ingest_delta(snap0, [p1], shard_configs(), ("userId",))

        n0 = snap0.n_rows
        assert info1.row_start == n0
        assert snap1.n_rows == n0 + info1.n_new_rows
        assert info1.delta_entities["userId"] <= {"u0", "a-new"}
        assert "a-new" in info1.delta_entities["userId"]
        assert info1.new_features == {"shardA": 1}  # f-late appended

        # frozen map growth: the old keys are a verbatim prefix
        keys0 = snap0.index_maps["shardA"].keys()
        keys1 = snap1.index_maps["shardA"].keys()
        assert keys1[: len(keys0)] == keys0
        assert len(keys1) == len(keys0) + 1

        # old rows are bitwise-untouched by the append: same csr bytes over
        # the old row range, same labels/uids prefix
        ptr0, idx0, dat0 = _csr_state(snap0.data.shard("shardA"))
        grown = snap1.data.shard("shardA").tocsr()[:n0]
        ptr1, idx1, dat1 = _csr_state(grown)
        np.testing.assert_array_equal(ptr0, ptr1)
        np.testing.assert_array_equal(idx0, idx1)
        np.testing.assert_array_equal(dat0, dat1)
        np.testing.assert_array_equal(
            np.asarray(snap0.data.labels), np.asarray(snap1.data.labels)[:n0]
        )
        np.testing.assert_array_equal(snap0.uids, snap1.uids[:n0])

    def test_rebuild_from_manifest_reproduces_the_accumulated_corpus(self, parts):
        # the restart contract: one read of the WHOLE manifest against the
        # final frozen maps == the progressively accumulated corpus, bitwise
        p0, p1 = parts
        snap0, _ = ingest_delta(None, [p0], shard_configs(), ("userId",))
        snap1, _ = ingest_delta(snap0, [p1], shard_configs(), ("userId",))

        data, maps, uids = read_merged_avro(
            [p0, p1], shard_configs(),
            index_maps=dict(snap1.index_maps), id_tags=("userId",),
        )
        assert maps["shardA"].keys() == snap1.index_maps["shardA"].keys()
        for side, other in [(data, snap1.data)]:
            np.testing.assert_array_equal(
                np.asarray(side.labels), np.asarray(other.labels)
            )
            np.testing.assert_array_equal(
                np.asarray(side.offsets), np.asarray(other.offsets)
            )
            np.testing.assert_array_equal(
                np.asarray(side.weights), np.asarray(other.weights)
            )
            np.testing.assert_array_equal(side.ids("userId"), other.ids("userId"))
            for a, b in zip(_csr_state(side.shard("shardA")),
                            _csr_state(other.shard("shardA"))):
                np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(np.asarray(uids, dtype=object), snap1.uids)

    def test_empty_delta_is_rejected(self):
        with pytest.raises(ValueError, match="no new files"):
            ingest_delta(None, [], shard_configs(), ("userId",))


# ------------------------------------------------------- active-set selection


class _FakeDataset(SimpleNamespace):
    pass


class _FakeModel:
    def __init__(self, known):
        self.entity_ids = tuple(known)


class TestActiveSelection:
    def _dataset(self, entities):
        return _FakeDataset(entity_ids=tuple(entities), n_entities=len(entities))

    def test_new_data_and_new_entity_rules(self):
        ds = self._dataset(["a", "b", "c", "d", "e"])
        sel = select_active_entities(
            ds, {"b"}, prev_model=_FakeModel(["a", "b", "c"])
        )
        np.testing.assert_array_equal(
            sel.mask, [False, True, False, True, True]
        )
        assert sel.n_active == 3
        assert sel.n_new_data == 1
        assert sel.n_new_entities == 2
        assert sel.n_gradient == 0

    def test_no_previous_model_activates_everything(self):
        ds = self._dataset(["a", "b"])
        sel = select_active_entities(ds, set(), prev_model=None)
        assert sel.n_active == 2 and sel.n_new_entities == 2

    def test_gradient_screen_catches_drifted_entities(self):
        ds = self._dataset(["a", "b", "c", "d"])
        norms = np.array([0.5, 9.0, 0.01, 4.0])
        sel = select_active_entities(
            ds, {"b"}, prev_model=_FakeModel(ds.entity_ids),
            gradient_norms=norms, gradient_threshold=1.0,
        )
        # b: new data; d: gradient screen; a/c below threshold stay frozen
        np.testing.assert_array_equal(sel.mask, [False, True, False, True])
        assert sel.n_gradient == 1  # d alone — b was already active

    def test_gradient_norm_shape_mismatch_raises(self):
        ds = self._dataset(["a", "b"])
        with pytest.raises(ValueError, match="gradient_norms shape"):
            select_active_entities(
                ds, set(), prev_model=_FakeModel(ds.entity_ids),
                gradient_norms=np.zeros(3), gradient_threshold=1.0,
            )

    def test_reservoir_masks_old_rows_and_keeps_the_delta(self):
        import jax.numpy as jnp

        @dataclasses.dataclass
        class Rows:
            weights: object

        data = Rows(weights=jnp.ones(10))
        out = ReservoirDownSampler(n_old=8, reservoir_size=4, seed=3).down_sample(data)
        w = np.asarray(out.weights)
        np.testing.assert_array_equal(w[8:], [1.0, 1.0])  # delta rows train
        kept = w[:8][w[:8] > 0]
        assert len(kept) == 4 and np.all(kept == 8 / 4)  # unbiased re-weight
        # deterministic: the same seed redraws the identical reservoir
        again = ReservoirDownSampler(n_old=8, reservoir_size=4, seed=3).down_sample(data)
        np.testing.assert_array_equal(w, np.asarray(again.weights))

    def test_reservoir_covering_all_old_rows_is_identity(self):
        import jax.numpy as jnp

        @dataclasses.dataclass
        class Rows:
            weights: object

        data = Rows(weights=jnp.ones(6))
        sampler = ReservoirDownSampler(n_old=4, reservoir_size=4, seed=0)
        assert sampler.down_sample(data) is data


# --------------------------------------------------- the generation loop e2e


@pytest.fixture(scope="module")
def loop_scenario(tmp_path_factory):
    """Bootstrap gen-1 over 8 users, then a delta targeting u0 + the brand-new
    entity a-new; capture both generations' states for the assertions."""
    rng = np.random.default_rng(7)
    root = tmp_path_factory.mktemp("continuous-loop")
    corpus = root / "corpus"
    os.makedirs(corpus)
    write_part(corpus / "part-00000.avro", rng, 200, USERS)

    trainer = make_trainer(corpus, root / "ckpt", export_dir=root / "export")
    r1 = trainer.poll_once()
    idle = trainer.poll_once()  # nothing new: no generation

    prev = trainer.models["per-user"]
    gen1_entities = prev.entity_ids
    gen1_coeffs = np.asarray(prev.coeffs).copy()
    gen1_fe = np.asarray(
        trainer.models["global"].model.coefficients.means
    ).copy()

    write_part(corpus / "part-00001.avro", rng, 40, ["u0", "a-new"])
    r2 = trainer.poll_once()
    return SimpleNamespace(
        root=root, corpus=corpus, trainer=trainer, r1=r1, r2=r2, idle=idle,
        gen1_entities=gen1_entities, gen1_coeffs=gen1_coeffs, gen1_fe=gen1_fe,
    )


class TestContinuousTrainer:
    def test_bootstrap_then_delta_generations(self, loop_scenario):
        s = loop_scenario
        assert s.r1.kind == "bootstrap" and s.r1.generation == 1
        assert s.idle is None
        assert s.r2.kind == "delta" and s.r2.generation == 2
        assert s.r2.n_new_rows == 40
        assert s.r2.n_rows == 240
        gens = list_generations(str(s.root / "ckpt"))
        assert [g for g, _ in gens] == [1, 2]

    def test_active_set_is_exactly_the_delta_entities(self, loop_scenario):
        stats = loop_scenario.r2.active["per-user"]
        # u0 (new data) + a-new (new entity); the other 7 users stay frozen
        assert stats["n_entities"] == 9
        assert stats["n_active"] == 2
        # a-new has new rows too, so it attributes to the new-data rule
        # (n_new_entities counts entities that are new WITHOUT new rows)
        assert stats["n_new_data"] == 2 and stats["n_new_entities"] == 0
        assert loop_scenario.r2.active_fraction == pytest.approx(2 / 9)

    def test_entity_rows_grow_at_the_tail(self, loop_scenario):
        s = loop_scenario
        grown = s.trainer.models["per-user"].entity_ids
        # a-new sorts before every u*, but stable growth appends it at the
        # TAIL: gen-1's row order is a verbatim prefix
        assert grown[: len(s.gen1_entities)] == s.gen1_entities
        assert grown[-1] == "a-new"

    def test_untouched_entities_survive_the_delta_bitwise(self, loop_scenario):
        s = loop_scenario
        grown_coeffs = np.asarray(s.trainer.models["per-user"].coeffs)
        touched = {"u0", "a-new"}
        for i, e in enumerate(s.gen1_entities):
            if e in touched:
                assert not np.array_equal(grown_coeffs[i], s.gen1_coeffs[i]), e
            else:
                np.testing.assert_array_equal(
                    grown_coeffs[i], s.gen1_coeffs[i], err_msg=e
                )
        # the fixed effect DID refresh (all rows train when no reservoir set)
        gen2_fe = np.asarray(s.trainer.models["global"].model.coefficients.means)
        assert not np.array_equal(gen2_fe, s.gen1_fe)

    def test_checkpoint_carries_the_corpus_state(self, loop_scenario):
        s = loop_scenario
        gens = list_generations(str(s.root / "ckpt"))
        state = load_generation(gens[-1][1])
        extra = state["extra"]["continuous"]
        assert extra["kind"] == "delta"
        assert len(extra["corpus_manifest"]["entries"]) == 2
        assert extra["n_rows"] == 240 and extra["n_new_rows"] == 40
        names = [
            str(n) for n in state["aux"]["index-map-shardA"]["names"]
        ]
        assert names == s.trainer.snapshot.index_maps["shardA"].keys()

    def test_exports_are_per_generation_directories(self, loop_scenario):
        s = loop_scenario
        assert sorted(os.listdir(s.root / "export")) == [
            "gen-00000001", "gen-00000002",
        ]

    def test_restart_resumes_from_the_committed_generation(self, loop_scenario):
        s = loop_scenario
        resumed = make_trainer(s.corpus, s.root / "ckpt")
        assert resumed.generation == 2
        assert len(resumed.manifest) == 2
        assert resumed.snapshot.n_rows == 240
        assert resumed.poll_once() is None  # nothing new: stays idle
        np.testing.assert_array_equal(
            np.asarray(resumed.models["per-user"].coeffs),
            np.asarray(s.trainer.models["per-user"].coeffs),
        )
        assert (
            resumed.models["per-user"].entity_ids
            == s.trainer.models["per-user"].entity_ids
        )


def test_mesh_backend_bootstrap_and_delta_generations(tmp_path):
    """PR 10 continuous wiring: a mesh-bearing trainer places every
    generation's datasets over the device mesh, trains the bootstrap through
    the sharded update program, runs the delta pass's active-set sub-buckets
    entity-sharded, and keeps every untouched entity's coefficients bitwise
    across generations — the same contract as the host backend."""
    from photon_ml_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(11)
    corpus = tmp_path / "corpus"
    os.makedirs(corpus)
    write_part(corpus / "part-00000.avro", rng, 200, USERS)
    trainer = make_trainer(corpus, tmp_path / "ckpt", mesh=make_mesh(8))
    r1 = trainer.poll_once()
    assert r1.kind == "bootstrap" and r1.generation == 1
    prev = trainer.models["per-user"]
    # the trained table lives entity-sharded with a device-multiple height
    assert prev.coeffs.sharding is not None
    assert prev.coeffs.shape[0] % 8 == 0
    gen1_bits = np.asarray(prev.coeffs).copy()
    gen1_ids = prev.entity_ids

    write_part(corpus / "part-00001.avro", rng, 40, ["u0", "a-new"])
    r2 = trainer.poll_once()
    assert r2.kind == "delta" and r2.generation == 2
    stats = r2.active["per-user"]
    assert stats["n_active"] == 2  # u0 (new data) + a-new (new entity)
    out = trainer.models["per-user"]
    new_bits = np.asarray(out.coeffs)
    for i, e in enumerate(gen1_ids):
        if e == "u0":
            continue
        np.testing.assert_array_equal(new_bits[i], gen1_bits[i], err_msg=str(e))
    # restart from the committed checkpoint resumes under the mesh
    trainer2 = make_trainer(corpus, tmp_path / "ckpt", mesh=make_mesh(8))
    assert trainer2.generation == 2
    assert trainer2.poll_once() is None  # nothing new


def test_run_streams_generations_to_the_callback(tmp_path):
    """run(on_generation=) is the run-forever mode: records stream to the
    callback and the returned list stays empty (nothing accumulates for the
    process lifetime)."""
    rng = np.random.default_rng(17)
    corpus = tmp_path / "corpus"
    os.makedirs(corpus)
    write_part(corpus / "part-00000.avro", rng, 120, USERS)
    t = make_trainer(corpus, tmp_path / "ckpt")
    seen = []
    out = t.run(
        poll_interval_s=0.0,
        max_generations=1,
        sleep=lambda s: None,
        on_generation=seen.append,
    )
    assert out == []
    assert [r.generation for r in seen] == [1]


def test_fe_reservoir_refuses_configured_down_sampling(tmp_path):
    """The reservoir replaces the FE coordinate's down-sampler on delta
    passes: combining it with a configured down.sampling.rate would train
    bootstrap and delta under different loss weightings, so construction
    must refuse."""
    coords = dict(
        parse_coordinate_configuration(c)
        for c in (FE_COORD + ",down.sampling.rate=0.5", RE_COORD)
    )
    with pytest.raises(ValueError, match="down.sampling.rate"):
        ContinuousTrainer(
            ContinuousTrainerConfig(
                corpus_paths=[str(tmp_path)],
                checkpoint_directory=str(tmp_path / "ckpt"),
                task=TaskType.LOGISTIC_REGRESSION,
                coordinate_configurations=coords,
                shard_configurations=shard_configs(),
                fe_reservoir=100,
            )
        )


def test_commit_fault_retry_does_not_double_ingest(tmp_path):
    """A poll that fails AT the commit fault point reverts the in-memory
    snapshot AND manifest view: a surviving caller's retried poll_once
    re-scans the same delta, ingests it exactly once, and commits the same
    generation an uninterrupted run would have."""
    rng = np.random.default_rng(11)
    corpus = tmp_path / "corpus"
    os.makedirs(corpus)
    write_part(corpus / "part-00000.avro", rng, 160, USERS)
    t = make_trainer(corpus, tmp_path / "ckpt")
    t.poll_once()
    write_part(corpus / "part-00001.avro", rng, 30, ["u0"])

    with armed("continuous.commit:raise"):
        with pytest.raises(InjectedFault):
            t.poll_once()
    # nothing durable or in-memory moved: the delta is still fully pending
    assert len(t.manifest) == 1
    assert t.snapshot.n_rows == 160
    assert t.generation == 1

    r = t.poll_once()  # in-process retry replays the delta cleanly
    assert r is not None and r.generation == 2
    assert r.n_rows == 190 and r.n_new_rows == 30
    state = load_generation(list_generations(str(tmp_path / "ckpt"))[-1][1])
    # the committed corpus state matches reality: no duplicated delta rows
    assert state["extra"]["continuous"]["n_rows"] == 190
    assert len(state["extra"]["continuous"]["corpus_manifest"]["entries"]) == 2


def test_restart_refuses_a_same_size_rewritten_part_file(tmp_path):
    """The restart rebuild verifies the persisted sha256 of every part file:
    a same-size rewrite (invisible to scan's size check) must fail loudly
    instead of warm-starting against a corpus the model never saw."""
    rng = np.random.default_rng(13)
    corpus = tmp_path / "corpus"
    os.makedirs(corpus)
    part = corpus / "part-00000.avro"
    write_part(part, rng, 120, USERS)
    make_trainer(corpus, tmp_path / "ckpt").poll_once()

    blob = bytearray(part.read_bytes())
    blob[len(blob) // 2] ^= 0xFF  # same size, different content
    part.write_bytes(bytes(blob))
    with pytest.raises(CorpusContractViolation, match="content changed"):
        make_trainer(corpus, tmp_path / "ckpt")


def test_gradient_screen_reactivates_drifted_entities(tmp_path):
    rng = np.random.default_rng(3)
    corpus = tmp_path / "corpus"
    os.makedirs(corpus)
    write_part(corpus / "part-00000.avro", rng, 160, USERS)
    # a threshold below the solver tolerance: every warm-started entity's
    # residual gradient exceeds it, so the catch-up rule re-solves them all
    t = make_trainer(corpus, tmp_path / "ckpt", gradient_threshold=1e-12)
    t.poll_once()
    write_part(corpus / "part-00001.avro", rng, 30, ["u0"])
    r = t.poll_once()
    stats = r.active["per-user"]
    assert stats["n_gradient"] > 0
    assert (
        stats["n_active"]
        == stats["n_new_data"] + stats["n_new_entities"] + stats["n_gradient"]
    )


def test_fe_reservoir_rides_the_delta_pass(tmp_path):
    rng = np.random.default_rng(4)
    corpus = tmp_path / "corpus"
    os.makedirs(corpus)
    write_part(corpus / "part-00000.avro", rng, 160, USERS)
    t = make_trainer(corpus, tmp_path / "ckpt", fe_reservoir=40)
    t.poll_once()
    fe1 = np.asarray(t.models["global"].model.coefficients.means).copy()
    write_part(corpus / "part-00001.avro", rng, 30, ["u0"])
    r = t.poll_once()
    assert r is not None and r.kind == "delta"
    # the reservoir-refreshed fixed effect still trains (and stays finite)
    fe2 = np.asarray(t.models["global"].model.coefficients.means)
    assert np.all(np.isfinite(fe2)) and not np.array_equal(fe1, fe2)


# ------------------------------------------- the closed train -> serve loop


def test_delta_generation_hot_swaps_into_live_serving(tmp_path):
    """The full photon-ml-tpu story: ContinuousTrainer commits a delta
    generation, PR 6's GenerationWatcher picks it up MID-TRAFFIC, and every
    served response is bitwise the direct engine call for the generation
    that served it."""
    from photon_ml_tpu.serving import FrontendConfig, clear_engine_cache
    from photon_ml_tpu.serving.hotswap import (
        GenerationWatcher,
        serve_from_checkpoint,
    )

    rng = np.random.default_rng(5)
    corpus = tmp_path / "corpus"
    os.makedirs(corpus)
    write_part(corpus / "part-00000.avro", rng, 200, USERS)
    trainer = make_trainer(corpus, tmp_path / "ckpt")
    trainer.poll_once()  # gen-1

    # a scoring request decoded against the trainer's frozen feature space
    val = tmp_path / "val"
    os.makedirs(val)
    write_part(val / "part-00000.avro", rng, 16, USERS)
    req, _, _ = read_merged_avro(
        [str(val / "part-00000.avro")], shard_configs(),
        index_maps=dict(trainer.snapshot.index_maps), id_tags=("userId",),
    )

    clear_engine_cache()
    frontend, manager = serve_from_checkpoint(
        str(tmp_path / "ckpt"), config=FrontendConfig(max_wait_ms=0.0)
    )
    served = []
    engines = {frontend.generation: frontend.engine}
    try:
        with GenerationWatcher(manager, poll_interval_s=0.02):
            for _ in range(3):
                fut = frontend.submit(req)
                served.append((fut.result(30), fut.generation))
            # commit the delta generation while traffic is flowing
            write_part(corpus / "part-00001.avro", rng, 40, ["u0"])
            r2 = trainer.poll_once()
            assert r2 is not None and r2.kind == "delta"
            deadline = time.monotonic() + 60
            while frontend.generation < r2.generation:
                fut = frontend.submit(req)
                served.append((fut.result(30), fut.generation))
                if time.monotonic() > deadline:
                    pytest.fail("watcher never swapped to the delta generation")
                time.sleep(0.01)
            engines[frontend.generation] = frontend.engine
            for _ in range(3):
                fut = frontend.submit(req)
                served.append((fut.result(30), fut.generation))
    finally:
        frontend.close()

    assert frontend.generation == r2.generation  # the swap happened
    gens_seen = {g for _, g in served}
    assert r2.generation in gens_seen  # and traffic was served on both sides
    for out, gen in served:
        np.testing.assert_array_equal(out, engines[gen].score(req))
    # the delta pass moved u0's model: the generations score differently
    assert not np.array_equal(engines[1].score(req), engines[r2.generation].score(req))
    clear_engine_cache()


# ----------------------------------------------------- continuous.* chaos bar


CONTINUOUS_POINTS = (
    "continuous.scan",
    "continuous.delta_ingest",
    "continuous.active_select",
    "continuous.commit",
)
# the out-of-core store's points only fire on a compaction/eviction-enabled
# pass: they get their own sweep over a scenario that exercises all of them
# (cold_link needs an INCREMENTAL compaction — a previous cold generation
# whose blocks the fold reuses; cold_delete needs retention expiry or an
# archive age-out on the swept pass)
STORE_POINTS = (
    "continuous.compact",
    "continuous.evict",
    "continuous.cold_write",
    "continuous.cold_link",
    "continuous.cold_delete",
)


def test_registry_covers_the_continuous_points():
    # importing photon_ml_tpu.continuous (top of this file) registers them
    assert set(CONTINUOUS_POINTS + STORE_POINTS) <= set(registered_fault_points())


@pytest.fixture(scope="module")
def chaos_scenario(tmp_path_factory):
    """Gen-1 committed, a delta part pending: the sweep replays the delta
    pass under crashes and compares exported generation bytes."""
    rng = np.random.default_rng(20260803)
    root = tmp_path_factory.mktemp("continuous-chaos")
    corpus = root / "corpus"
    os.makedirs(corpus)
    write_part(corpus / "part-00000.avro", rng, 200, USERS)
    base_ckpt = root / "ckpt-base"
    make_trainer(corpus, base_ckpt).poll_once()  # commit gen-1
    write_part(corpus / "part-00001.avro", rng, 40, ["u0", "a-new"])

    def run_loop(ckpt, export):
        t = make_trainer(corpus, ckpt, export_dir=export)
        while t.poll_once() is not None:
            pass
        return t

    # the uninterrupted reference (restore gen-1 -> delta pass -> gen-2);
    # a fresh export dir re-exports gen-1 idempotently at restore
    ref_export = root / "export-ref"
    shutil.copytree(base_ckpt, root / "ckpt-ref")
    run_loop(root / "ckpt-ref", ref_export)
    return SimpleNamespace(
        base_ckpt=base_ckpt, ref_export=ref_export, run_loop=run_loop
    )


@pytest.mark.chaos
class TestContinuousChaos:
    def test_delta_export_is_deterministic(self, chaos_scenario, tmp_path):
        # the sweep's premise: two uninterrupted delta runs export the same bytes
        shutil.copytree(chaos_scenario.base_ckpt, tmp_path / "ckpt")
        chaos_scenario.run_loop(tmp_path / "ckpt", tmp_path / "export")
        assert_trees_identical(
            str(chaos_scenario.ref_export), str(tmp_path / "export")
        )

    @pytest.mark.parametrize("point", CONTINUOUS_POINTS)
    def test_crash_mid_delta_resumes_to_identical_generation_bytes(
        self, chaos_scenario, tmp_path, point
    ):
        shutil.copytree(chaos_scenario.base_ckpt, tmp_path / "ckpt")
        _, outcome = run_with_crash_at(
            lambda: chaos_scenario.run_loop(tmp_path / "ckpt", tmp_path / "export"),
            point,
        )
        # every continuous.* point sits ON the delta path: the crash must fire
        assert outcome.crashed and outcome.restarts >= 1
        assert_trees_identical(
            str(chaos_scenario.ref_export), str(tmp_path / "export")
        )


# ==========================================================================
# Out-of-core corpus store: manifest compaction, cold tier, sliding window,
# entity eviction (continuous/store.py, compaction.py)
# ==========================================================================


class TestManifestCompaction:
    def test_compact_folds_entries_and_scan_still_diffs(self, tmp_path):
        a, b = str(tmp_path / "part-a.avro"), str(tmp_path / "part-b.avro")
        _touch(a, b"aaaa")
        _touch(b, b"bbbbbb")
        m = CorpusManifest().extend([a, b])
        folded = m.compact(n_rows=100)
        assert folded.entries == ()
        assert len(folded) == 2  # total files ever, across the fold
        assert folded.paths == (a, b)
        assert folded.live_paths == ()
        assert folded.compacted.n_rows == 100
        # already-ingested files stay known to the scan
        assert folded.scan([str(tmp_path)]) == []
        c = str(tmp_path / "part-c.avro")
        _touch(c, b"cc")
        assert folded.scan([str(tmp_path)]) == [c]
        # extend CARRIES the fold (the regression that double-ingested
        # compacted files after the next delta)
        grown = folded.extend([c])
        assert grown.compacted == folded.compacted
        assert grown.scan([str(tmp_path)]) == []
        assert len(grown) == 3

    def test_compacted_file_may_vanish_but_not_change_size(self, tmp_path):
        a = str(tmp_path / "part-a.avro")
        _touch(a, b"payload")
        folded = CorpusManifest().extend([a]).compact(n_rows=10)
        os.remove(a)  # the upstream archived it: the cold tier owns the rows
        assert folded.scan([str(tmp_path)]) == []
        folded.verify_fingerprints()  # compacted files are never re-read
        # but a REUSED path with different content must still fail loudly
        _touch(a, b"a-brand-new-file!")
        with pytest.raises(CorpusContractViolation, match="append-only"):
            folded.scan([str(tmp_path)])

    def test_rollup_digest_chains_across_folds(self, tmp_path):
        a, b = str(tmp_path / "a.avro"), str(tmp_path / "b.avro")
        _touch(a, b"aaaa")
        _touch(b, b"bb")
        once = CorpusManifest().extend([a]).compact(n_rows=1)
        twice = once.extend([b]).compact(n_rows=2)
        assert twice.compacted.n_files == 2
        assert twice.compacted.rollup_sha256 != once.compacted.rollup_sha256
        # pure function of the ingest history: same folds, same digest
        again = CorpusManifest().extend([a]).compact(1).extend([b]).compact(2)
        assert again.compacted.rollup_sha256 == twice.compacted.rollup_sha256

    def test_round_trip_with_compacted_history(self, tmp_path):
        a = str(tmp_path / "a.avro")
        _touch(a, b"aaaa")
        m = CorpusManifest().extend([a]).compact(n_rows=7)
        again = CorpusManifest.from_dict(m.to_dict())
        assert again == m


def _trees_identical(a, b):
    import filecmp

    files_a = sorted(
        os.path.relpath(os.path.join(r, f), a)
        for r, _, fs in os.walk(a) for f in fs
    )
    files_b = sorted(
        os.path.relpath(os.path.join(r, f), b)
        for r, _, fs in os.walk(b) for f in fs
    )
    assert files_a == files_b
    for rel in files_a:
        assert filecmp.cmp(os.path.join(a, rel), os.path.join(b, rel),
                           shallow=False), rel


class TestCorpusStoreTiers:
    def test_compacted_corpus_reproduces_the_accumulated_corpus_bitwise(
        self, tmp_path
    ):
        """The restart contract through the cold tier: after a compaction,
        materializing from (cold blocks + live files) is bitwise the corpus
        a plain re-read of every original part file produces."""
        rng = np.random.default_rng(31)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        write_part(corpus / "part-00000.avro", rng, 150, USERS)
        t = make_trainer(corpus, tmp_path / "ckpt", compact_every=2,
                         cold_block_rows=64)
        t.poll_once()
        write_part(corpus / "part-00001.avro", rng, 40, ["u0", "a-new"])
        r = t.poll_once()
        assert r.compacted and len(t.manifest.entries) == 0
        write_part(corpus / "part-00002.avro", rng, 30, ["u1"])
        t.poll_once()  # gen 3: one live segment on top of the cold tier

        # fresh trainer: cold blocks + one live re-decode, no full re-read
        t2 = make_trainer(corpus, tmp_path / "ckpt", compact_every=2,
                          cold_block_rows=64)
        view, ref = t2.snapshot, t.snapshot
        np.testing.assert_array_equal(
            np.asarray(view.data.labels), np.asarray(ref.data.labels)
        )
        np.testing.assert_array_equal(view.uids, ref.uids)
        np.testing.assert_array_equal(view.row_gens, ref.row_gens)
        np.testing.assert_array_equal(
            view.data.ids("userId"), ref.data.ids("userId")
        )
        for x, y in zip(_csr_state(view.data.shard("shardA")),
                        _csr_state(ref.data.shard("shardA"))):
            np.testing.assert_array_equal(x, y)
        # and equally bitwise vs a cold-free re-read of EVERY original file
        data, _, uids = read_merged_avro(
            list(t.manifest.paths), shard_configs(),
            index_maps=dict(ref.index_maps), id_tags=("userId",),
        )
        np.testing.assert_array_equal(
            np.asarray(data.labels), np.asarray(ref.data.labels)
        )
        for x, y in zip(_csr_state(data.shard("shardA")),
                        _csr_state(ref.data.shard("shardA"))):
            np.testing.assert_array_equal(x, y)
        np.testing.assert_array_equal(
            np.asarray(uids, dtype=object), ref.uids
        )

    def test_restart_survives_archived_away_part_files(self, tmp_path):
        """Once compacted, the original part files may be deleted upstream:
        restart reads the cold tier instead, and the next delta still
        commits (the out-of-core story: disk tier owns the history)."""
        rng = np.random.default_rng(33)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        write_part(corpus / "part-00000.avro", rng, 120, USERS)
        t = make_trainer(corpus, tmp_path / "ckpt", compact_every=2)
        t.poll_once()
        write_part(corpus / "part-00001.avro", rng, 40, ["u0"])
        r = t.poll_once()
        assert r.compacted
        before = np.asarray(t.models["per-user"].coeffs).copy()
        os.remove(corpus / "part-00000.avro")
        os.remove(corpus / "part-00001.avro")

        t2 = make_trainer(corpus, tmp_path / "ckpt", compact_every=2)
        assert t2.generation == 2
        assert t2.snapshot.n_rows == 160
        np.testing.assert_array_equal(
            np.asarray(t2.models["per-user"].coeffs), before
        )
        write_part(corpus / "part-00002.avro", rng, 30, ["u1"])
        r3 = t2.poll_once()
        assert r3 is not None and r3.generation == 3 and r3.n_rows == 190

    def test_corrupt_cold_block_fails_restart_loudly(self, tmp_path):
        from photon_ml_tpu.continuous import ColdStoreCorruption
        from photon_ml_tpu.resilience import corrupt_file

        rng = np.random.default_rng(35)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        write_part(corpus / "part-00000.avro", rng, 100, USERS)
        t = make_trainer(corpus, tmp_path / "ckpt", compact_every=1,
                         cold_block_rows=32)
        t.poll_once()
        pool = tmp_path / "ckpt" / "corpus-store" / "blocks"
        victim = sorted(f for f in os.listdir(pool) if f.endswith(".npz"))[0]
        corrupt_file(str(pool / victim))
        with pytest.raises(ColdStoreCorruption, match="checksum mismatch"):
            make_trainer(corpus, tmp_path / "ckpt", compact_every=1,
                         cold_block_rows=32)

    def test_cold_generations_are_pruned(self, tmp_path):
        rng = np.random.default_rng(37)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        write_part(corpus / "part-00000.avro", rng, 60, USERS)
        t = make_trainer(corpus, tmp_path / "ckpt", compact_every=1)
        t.poll_once()
        for k in range(1, 4):
            write_part(corpus / f"part-{k:05d}.avro", rng, 20, ["u0"])
            t.poll_once()
        store_dir = tmp_path / "ckpt" / "corpus-store"
        colds = sorted(n for n in os.listdir(store_dir) if n.startswith("cold-"))
        # keep_cold=2: the referenced cold gen + one rollback step
        assert colds == ["cold-00000003", "cold-00000004"]


def _cold_manifest(ckpt, cold_id):
    import json

    path = os.path.join(
        str(ckpt), "corpus-store", f"cold-{cold_id:08d}", "manifest.json"
    )
    with open(path) as f:
        return json.load(f)


def _pool_shas(ckpt):
    pool = os.path.join(str(ckpt), "corpus-store", "blocks")
    return {
        n[: -len(".npz")]
        for n in os.listdir(pool)
        if n.endswith(".npz") and ".tmp" not in n
    }


class TestColdBlockReuse:
    """The O(delta) cold tier: incremental compactions adopt unchanged
    blocks by reference into the content-addressed pool instead of
    re-encoding O(history)."""

    def test_second_compaction_reuses_blocks_and_writes_only_the_delta(
        self, tmp_path
    ):
        rng = np.random.default_rng(81)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        # 128 bootstrap rows = exactly 2 blocks of 64: the first fold's full
        # blocks must ride into the second fold untouched
        write_part(corpus / "part-00000.avro", rng, 128, USERS)
        t = make_trainer(corpus, tmp_path / "ckpt", compact_every=2,
                         cold_block_rows=64)
        t.poll_once()
        write_part(corpus / "part-00001.avro", rng, 30, ["u0"])
        r2 = t.poll_once()
        assert r2.compacted
        assert r2.cold_stats["blocks_reused"] == 0  # nothing cold to reuse yet
        first_blocks = {
            b["sha256"] for b in _cold_manifest(tmp_path / "ckpt", 2)["blocks"]
        }
        write_part(corpus / "part-00002.avro", rng, 30, ["u0"])
        t.poll_once()
        write_part(corpus / "part-00003.avro", rng, 30, ["u0"])
        r4 = t.poll_once()
        assert r4.compacted
        stats = r4.cold_stats
        # the 2 full bootstrap blocks reuse by reference; only the partial
        # tail + the two live deltas re-encode — O(delta + tail block)
        assert stats["blocks_reused"] == 2
        assert stats["bytes_reused"] > 0
        assert stats["blocks_written"] <= 2
        assert stats["bytes_written"] < stats["bytes_reused"]
        second = _cold_manifest(tmp_path / "ckpt", 4)
        reused = {b["sha256"] for b in second["blocks"]} & first_blocks
        assert len(reused) == 2  # same digests, same bytes, never rewritten
        # the restart contract still holds bitwise through the reused blocks
        t2 = make_trainer(corpus, tmp_path / "ckpt", compact_every=2,
                          cold_block_rows=64)
        np.testing.assert_array_equal(
            np.asarray(t2.snapshot.data.labels),
            np.asarray(t.snapshot.data.labels),
        )
        np.testing.assert_array_equal(t2.snapshot.uids, t.snapshot.uids)

    def test_index_map_growth_never_rewrites_cold_blocks(self, tmp_path):
        """Block-level column re-encoding: each cold block persists its OWN
        sorted column-id vocabulary (global frozen-``IndexMap`` ids) plus
        block-local indices, remapped back to global at read time. A later
        ``IndexMap.extend`` — the feature axis growing — therefore changes
        no existing block's bytes: the next compaction adopts every full
        pre-growth block by reference (zero rewrites), and the wider-width
        corpus still materializes bitwise against a frozen-map re-read of
        every original part file."""
        rng = np.random.default_rng(85)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        write_part(corpus / "part-00000.avro", rng, 128, USERS)
        t = make_trainer(corpus, tmp_path / "ckpt", compact_every=2,
                         cold_block_rows=64)
        t.poll_once()
        write_part(corpus / "part-00001.avro", rng, 30, ["u0"])
        r2 = t.poll_once()
        assert r2.compacted
        width0 = t.snapshot.index_maps["shardA"].size
        first_blocks = {
            b["sha256"] for b in _cold_manifest(tmp_path / "ckpt", 2)["blocks"]
        }
        # the written blocks carry the vocabulary encoding: sorted global
        # column ids + local indices that never reach past the vocabulary
        pool = os.path.join(str(tmp_path / "ckpt"), "corpus-store", "blocks")
        block_file = os.path.join(pool, sorted(first_blocks)[0] + ".npz")
        with np.load(block_file, allow_pickle=False) as z:
            colids = z["feat__shardA__colids"]
            local = z["feat__shardA__indices"]
        assert np.all(np.diff(colids) > 0) and int(colids.max()) < width0
        assert local.size == 0 or int(local.max()) < len(colids)

        # grow the feature axis: this delta's new feature extends the map
        write_part(corpus / "part-00002.avro", rng, 30, ["u1"],
                   extra_feature="f_wide")
        t.poll_once()
        write_part(corpus / "part-00003.avro", rng, 30, ["u1"])
        r4 = t.poll_once()
        assert r4.compacted
        assert t.snapshot.index_maps["shardA"].size == width0 + 1
        assert t.snapshot.data.shard("shardA").shape[1] == width0 + 1
        # zero pre-existing blocks rewritten: both full pre-growth blocks
        # ride into the post-growth generation by digest reference
        assert r4.cold_stats["blocks_reused"] == 2
        second = _cold_manifest(tmp_path / "ckpt", 4)
        assert len({b["sha256"] for b in second["blocks"]} & first_blocks) == 2

        # bitwise corpus through the mixed-width cold tier: a fresh restart
        # (cold blocks + live re-decode) vs a cold-free re-read of EVERY
        # original part file under the final frozen maps
        t2 = make_trainer(corpus, tmp_path / "ckpt", compact_every=2,
                          cold_block_rows=64)
        view, ref = t2.snapshot, t.snapshot
        np.testing.assert_array_equal(
            np.asarray(view.data.labels), np.asarray(ref.data.labels)
        )
        np.testing.assert_array_equal(view.uids, ref.uids)
        for x, y in zip(_csr_state(view.data.shard("shardA")),
                        _csr_state(ref.data.shard("shardA"))):
            np.testing.assert_array_equal(x, y)
        data, _, uids = read_merged_avro(
            list(t.manifest.paths), shard_configs(),
            index_maps=dict(ref.index_maps), id_tags=("userId",),
        )
        np.testing.assert_array_equal(
            np.asarray(data.labels), np.asarray(ref.data.labels)
        )
        for x, y in zip(_csr_state(data.shard("shardA")),
                        _csr_state(ref.data.shard("shardA"))):
            np.testing.assert_array_equal(x, y)

    def test_prune_never_deletes_a_block_the_surviving_generation_references(
        self, tmp_path
    ):
        """The refcount contract: the manifests of the kept cold generations
        ARE the pool's reference set — prune_cold garbage-collects exactly
        the unreferenced blocks, never a referenced one."""
        rng = np.random.default_rng(83)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        write_part(corpus / "part-00000.avro", rng, 128, USERS)
        t = make_trainer(corpus, tmp_path / "ckpt", compact_every=1,
                         cold_block_rows=64)
        t.poll_once()
        for k in (1, 2, 3):
            write_part(corpus / f"part-{k:05d}.avro", rng, 20, ["u0"])
            t.poll_once()
        # keep_cold=2 kept cold-3 and cold-4; every sha they reference must
        # exist in the pool, and nothing else may remain
        referenced = {
            b["sha256"]
            for cid in (3, 4)
            for b in _cold_manifest(tmp_path / "ckpt", cid)["blocks"]
        }
        assert _pool_shas(tmp_path / "ckpt") == referenced
        # an orphan pool block (crashed compaction leftovers) sweeps; the
        # referenced blocks survive the same prune
        pool = tmp_path / "ckpt" / "corpus-store" / "blocks"
        orphan = pool / ("ab" * 32 + ".npz")
        orphan.write_bytes(b"orphaned by a crash")
        t.store.prune_cold(referenced=4)
        assert not orphan.exists()
        assert _pool_shas(tmp_path / "ckpt") == referenced

    def test_unreadable_cold_manifest_skips_pool_gc_conservatively(
        self, tmp_path
    ):
        """A damaged manifest makes the reference set unknowable: the GC
        must refuse to delete ANY pool block (the damage itself fails loudly
        at the next read) rather than drop one a generation still needs."""
        rng = np.random.default_rng(84)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        write_part(corpus / "part-00000.avro", rng, 64, USERS)
        t = make_trainer(corpus, tmp_path / "ckpt", compact_every=1,
                         cold_block_rows=64)
        t.poll_once()
        before = _pool_shas(tmp_path / "ckpt")
        man = (tmp_path / "ckpt" / "corpus-store" / "cold-00000001"
               / "manifest.json")
        man.write_text(man.read_text() + " ")  # checksum now mismatches
        pool = tmp_path / "ckpt" / "corpus-store" / "blocks"
        orphan = pool / ("cd" * 32 + ".npz")
        orphan.write_bytes(b"would be garbage")
        t.store.prune_cold(referenced=1)
        assert orphan.exists()  # GC skipped: nothing deleted
        assert before <= _pool_shas(tmp_path / "ckpt")

    def test_legacy_in_dir_cold_generation_reads_and_links_into_the_pool(
        self, tmp_path
    ):
        """Backward compat for format-1 cold manifests (blocks inside the
        generation directory): restart reads them verbatim, and the next
        compaction adopts their blocks into the pool by hard link (fallback
        copy) instead of re-encoding."""
        import json

        rng = np.random.default_rng(85)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        write_part(corpus / "part-00000.avro", rng, 128, USERS)
        t = make_trainer(corpus, tmp_path / "ckpt", compact_every=1,
                         cold_block_rows=64)
        t.poll_once()
        ref_labels = np.asarray(t.snapshot.data.labels).copy()
        del t
        # rewrite cold-1 in the legacy layout: blocks move INTO the dir
        # under block-<k>.npz names, the manifest gains "name" per block
        store_dir = tmp_path / "ckpt" / "corpus-store"
        cold = store_dir / "cold-00000001"
        meta = _cold_manifest(tmp_path / "ckpt", 1)
        meta["format"] = 1
        for k, b in enumerate(meta["blocks"]):
            b["name"] = f"block-{k:06d}.npz"
            b.pop("nbytes", None)
            shutil.copy(
                store_dir / "blocks" / f"{b['sha256']}.npz", cold / b["name"]
            )
        man = cold / "manifest.json"
        man.write_text(json.dumps(meta))
        (cold / "manifest.json.sha256").write_text(
            hashlib.sha256(man.read_bytes()).hexdigest() + "\n"
        )
        shutil.rmtree(store_dir / "blocks")  # pure v1 store on disk

        t2 = make_trainer(corpus, tmp_path / "ckpt", compact_every=1,
                          cold_block_rows=64)
        np.testing.assert_array_equal(
            np.asarray(t2.snapshot.data.labels), ref_labels
        )
        write_part(corpus / "part-00001.avro", rng, 20, ["u0"])
        r2 = t2.poll_once()
        assert r2.compacted
        # the 2 full legacy blocks were adopted without re-encoding
        assert r2.cold_stats["blocks_reused"] == 2
        meta2 = _cold_manifest(tmp_path / "ckpt", 2)
        assert int(meta2["format"]) == 2
        assert all("name" not in b for b in meta2["blocks"])
        # and the linked bytes still verify + materialize bitwise
        t3 = make_trainer(corpus, tmp_path / "ckpt", compact_every=1,
                          cold_block_rows=64)
        np.testing.assert_array_equal(
            np.asarray(t3.snapshot.data.labels)[: len(ref_labels)], ref_labels
        )

    def test_crash_between_link_and_manifest_publish_replays_clean(
        self, tmp_path
    ):
        """Kill the fold between block adoption and manifest publish: the
        replay must converge to zero duplicate/orphan pool blocks and a
        bitwise-identical materialization vs an uninterrupted run."""
        rng = np.random.default_rng(87)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        write_part(corpus / "part-00000.avro", rng, 128, USERS)
        kw = dict(compact_every=2, cold_block_rows=64)
        t = make_trainer(corpus, tmp_path / "ckpt", **kw)
        t.poll_once()
        write_part(corpus / "part-00001.avro", rng, 30, ["u0"])
        t.poll_once()  # cold-2 on disk
        write_part(corpus / "part-00002.avro", rng, 30, ["u0"])
        t.poll_once()
        del t
        shutil.copytree(tmp_path / "ckpt", tmp_path / "ckpt-ref")
        write_part(corpus / "part-00003.avro", rng, 30, ["u0"])  # pending gen 4

        def run_loop(ckpt):
            t = make_trainer(corpus, ckpt, **kw)
            while t.poll_once() is not None:
                pass
            return t

        ref = run_loop(tmp_path / "ckpt-ref")
        assert ref.last_result.compacted
        _, outcome = run_with_crash_at(
            lambda: run_loop(tmp_path / "ckpt"), "continuous.cold_link"
        )
        assert outcome.crashed and outcome.restarts >= 1
        assert_trees_identical(
            str(tmp_path / "ckpt-ref"), str(tmp_path / "ckpt")
        )
        # zero duplicates: the pool is exactly the union of the surviving
        # manifests' references
        referenced = {
            b["sha256"]
            for cid in (2, 4)
            for b in _cold_manifest(tmp_path / "ckpt", cid)["blocks"]
        }
        assert _pool_shas(tmp_path / "ckpt") == referenced


class TestRetention:
    """Cold-tier row deletion: sliding-window/time-decay aging can now DROP
    rows at compaction — only ever rows whose training weight is already
    zero, so the trained model is bitwise unaffected."""

    def test_retention_deletes_history_without_changing_the_model(
        self, tmp_path
    ):
        rng = np.random.default_rng(91)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        base = dict(window_mode="sliding", window_generations=2,
                    compact_every=2, cold_block_rows=64)
        write_part(corpus / "part-00000.avro", rng, 128, USERS)
        t = make_trainer(corpus, tmp_path / "ckpt", max_row_age_gens=2,
                         **base)
        tw = make_trainer(corpus, tmp_path / "ckpt-tw", **base)  # full history
        t.poll_once()
        tw.poll_once()
        dropped = 0
        for k in range(1, 7):
            write_part(corpus / f"part-{k:05d}.avro", rng, 30, USERS)
            r = t.poll_once()
            tw.poll_once()
            if r.compacted:
                dropped += r.cold_stats["rows_dropped"]
        assert dropped > 0
        # the retained tier holds only the window's generations ...
        assert t.store.total_rows < tw.store.total_rows
        assert t.store.cold_rows <= 2 * 30 + 30  # last 2 gens + block slack
        # ... and the models are bitwise the full-history trainer's
        np.testing.assert_array_equal(
            np.asarray(t.models["per-user"].coeffs),
            np.asarray(tw.models["per-user"].coeffs),
        )
        np.testing.assert_array_equal(
            np.asarray(t.models["global"].model.coefficients.means),
            np.asarray(tw.models["global"].model.coefficients.means),
        )
        # restart from the retained store replays cleanly
        t2 = make_trainer(corpus, tmp_path / "ckpt", max_row_age_gens=2,
                          **base)
        np.testing.assert_array_equal(
            np.asarray(t2.snapshot.data.labels),
            np.asarray(t.snapshot.data.labels),
        )

    def test_max_cold_rows_caps_the_tier_at_block_granularity(self, tmp_path):
        rng = np.random.default_rng(93)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        write_part(corpus / "part-00000.avro", rng, 128, USERS)
        t = make_trainer(corpus, tmp_path / "ckpt", window_mode="sliding",
                         window_generations=2, compact_every=2,
                         cold_block_rows=32, max_cold_rows=96)
        t.poll_once()
        for k in range(1, 6):  # gens 2..6: the last pass compacts
            write_part(corpus / f"part-{k:05d}.avro", rng, 30, USERS)
            r = t.poll_once()
        assert r.compacted
        # the cap is best-effort at block granularity: at most one extra
        # block beyond the cap, and never an in-window row
        assert t.store.cold_rows <= 96 + 32
        assert t.snapshot.n_rows == 60  # the window is intact
        assert r.cold_stats["blocks_dropped"] > 0

    def test_retention_config_is_validated(self, tmp_path):
        with pytest.raises(ValueError, match="bounded training window"):
            make_trainer(tmp_path, tmp_path / "c", max_row_age_gens=4,
                         compact_every=2)
        with pytest.raises(ValueError, match="cover the training window"):
            make_trainer(tmp_path, tmp_path / "c", window_mode="sliding",
                         window_generations=4, compact_every=2,
                         max_row_age_gens=2)
        with pytest.raises(ValueError, match="compaction time"):
            make_trainer(tmp_path, tmp_path / "c", window_mode="sliding",
                         window_generations=2, max_row_age_gens=4)
        with pytest.raises(ValueError, match="bounded training window"):
            make_trainer(tmp_path, tmp_path / "c", max_cold_rows=100,
                         compact_every=2)
        with pytest.raises(ValueError, match="evict_idle_generations"):
            make_trainer(tmp_path, tmp_path / "c", compact_every=2,
                         archive_max_age_gens=3)


class TestStreamedBootstrap:
    def test_fresh_start_against_a_backlog_matches_the_live_trainer_bitwise(
        self, tmp_path
    ):
        """max_files_per_pass=1 drains a pre-existing deep corpus through
        the same windowed delta passes a live trainer ran as the files
        arrived: every committed generation — the WHOLE checkpoint tree,
        corpus store included — is byte-identical, while resident corpus
        bytes stay O(window + delta) instead of O(corpus)."""
        rng = np.random.default_rng(95)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        base = dict(window_mode="sliding", window_generations=2,
                    compact_every=2, cold_block_rows=64)
        # the live trainer polls after each file lands
        live = make_trainer(corpus, tmp_path / "ckpt-live", **base)
        for k in range(7):
            write_part(corpus / f"part-{k:05d}.avro", rng, 30, USERS)
            live.poll_once()
        # the streamed bootstrap starts fresh against the full backlog
        stream = make_trainer(corpus, tmp_path / "ckpt-stream",
                              max_files_per_pass=1, **base)
        peaks = []
        while stream.poll_once() is not None:
            peaks.append(stream.store.resident_corpus_bytes)
        assert stream.generation == live.generation == 7
        assert_trees_identical(
            str(tmp_path / "ckpt-live"), str(tmp_path / "ckpt-stream")
        )
        # bounded resident bytes: the O(corpus) one-shot bootstrap's view
        # dwarfs the streamed peak
        onebig = make_trainer(corpus, tmp_path / "ckpt-big", **base)
        onebig.poll_once()
        assert max(peaks) < onebig.store.resident_corpus_bytes

    def test_capped_pass_ingests_oldest_files_first(self, tmp_path):
        rng = np.random.default_rng(97)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        for k in range(3):
            write_part(corpus / f"part-{k:05d}.avro", rng, 20, USERS)
        t = make_trainer(corpus, tmp_path / "ckpt", max_files_per_pass=2)
        r1 = t.poll_once()
        assert r1.n_new_rows == 40  # parts 0 and 1
        assert len(t.manifest.entries) == 2
        assert t.manifest.entries[0].path.endswith("part-00000.avro")
        r2 = t.poll_once()
        assert r2.n_new_rows == 20  # the backlog tail
        assert t.poll_once() is None


class TestArchiveAgeOut:
    def test_archive_ages_out_old_entries_but_keeps_warm_readmission(
        self, tmp_path
    ):
        """Two eviction waves; the age-out horizon drops the first wave's
        archive entries at a later compaction while the second wave's
        survive — a surviving entity still re-admits WARM from its archived
        coefficients, an aged-out one re-solves from zero like a brand-new
        entity."""
        rng = np.random.default_rng(99)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        kw = dict(window_mode="sliding", window_generations=2,
                  evict_idle_generations=2, compact_every=3,
                  archive_max_age_gens=3, cold_block_rows=64)
        write_part(corpus / "part-00000.avro", rng, 160, USERS)
        t = make_trainer(corpus, tmp_path / "ckpt", **kw)
        t.poll_once()
        # wave 1: u1..u7 idle -> evicted at gen 4 (evicted_at=4)
        for k in (1, 2, 3):
            write_part(corpus / f"part-{k:05d}.avro", rng, 30, ["u0"])
            t.poll_once()
        assert "u1" in t.evicted["per-user"]
        # u1 re-admits at gen 5, idles again -> re-evicted (evicted_at=8)
        write_part(corpus / "part-00004.avro", rng, 30, ["u0", "u1"])
        t.poll_once()
        for k in (5, 6, 7):
            write_part(corpus / f"part-{k:05d}.avro", rng, 30, ["u0"])
            t.poll_once()
        assert "u1" in t.evicted["per-user"]
        archive = t.store.archive_load("per-user")
        gens_by_id = dict(
            zip(archive["entity_ids"].tolist(), archive["evicted_at"].tolist())
        )
        assert gens_by_id["u1"] > gens_by_id["u2"]
        # gen 9 compacts: cutoff 9-3=6 drops wave 1 (evicted_at=4), keeps u1
        write_part(corpus / "part-00008.avro", rng, 30, ["u0"])
        r9 = t.poll_once()
        assert r9.compacted
        archive = t.store.archive_load("per-user")
        assert set(archive["entity_ids"].tolist()) == {"u1"}
        assert "u2" in t.evicted["per-user"]  # still evicted, archive gone
        u1_archived = archive["coeffs"][0].copy()
        assert np.any(u1_archived != 0)

        # surviving entry: warm re-admission still works
        write_part(corpus / "part-00009.avro", rng, 12, ["u0", "u1"])
        r10 = t.poll_once()
        assert r10.active["per-user"]["n_readmitted"] == 1
        assert "u1" not in t.evicted["per-user"]
        # aged-out entry: re-admits cold (no archive row to inject)
        write_part(corpus / "part-00010.avro", rng, 12, ["u0", "u2"])
        r11 = t.poll_once()
        assert r11.active["per-user"]["n_readmitted"] == 0
        assert "u2" not in t.evicted["per-user"]
        assert t.models["per-user"].row_for_entity("u2") >= 0

    def test_past_horizon_entry_never_warm_starts_even_before_deletion(
        self, tmp_path
    ):
        """The horizon applies AT INJECTION TIME, not at deletion time: an
        archive entry past it never warm-starts even while physically
        present (physical deletion is lazy, at compaction cadence). This is
        the crash-replay symmetry — a crash between the archive rewrite and
        the commit cannot make a replayed pass warm-start an entity the
        uninterrupted run re-solved from zero."""
        rng = np.random.default_rng(101)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        kw = dict(window_mode="sliding", window_generations=2,
                  evict_idle_generations=2, compact_every=50,  # no compaction
                  archive_max_age_gens=2, cold_block_rows=64)
        write_part(corpus / "part-00000.avro", rng, 160, USERS)
        t = make_trainer(corpus, tmp_path / "ckpt", **kw)
        t.poll_once()
        for k in (1, 2, 3, 4, 5):
            write_part(corpus / f"part-{k:05d}.avro", rng, 30, ["u0"])
            t.poll_once()
        assert "u1" in t.evicted["per-user"]  # evicted at gen 4
        archive = t.store.archive_load("per-user")
        assert "u1" in archive["entity_ids"].tolist()  # physically present
        # gen 7: u1 reappears, but its entry (evicted_at=4) is past the
        # horizon (7 - 2 = 5) -> cold re-admission despite the bytes on disk
        write_part(corpus / "part-00006.avro", rng, 30, ["u0", "u1"])
        r7 = t.poll_once()
        assert r7.active["per-user"]["n_readmitted"] == 0
        assert "u1" not in t.evicted["per-user"]
        assert t.models["per-user"].row_for_entity("u1") >= 0


class TestSlidingWindow:
    def test_view_is_bounded_and_old_rows_age_out(self, tmp_path):
        rng = np.random.default_rng(41)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        write_part(corpus / "part-00000.avro", rng, 100, USERS)
        t = make_trainer(corpus, tmp_path / "ckpt", window_mode="sliding",
                         window_generations=2)
        t.poll_once()
        views = []
        for k in range(1, 5):
            write_part(corpus / f"part-{k:05d}.avro", rng, 30, USERS)
            r = t.poll_once()
            views.append((r.generation, r.view_rows, r.n_rows))
        # window 2: from gen 3 on the view is exactly the last two deltas
        assert views[-1] == (5, 60, 220)
        assert views[-2] == (4, 60, 190)
        gens = np.unique(t.snapshot.row_gens)
        np.testing.assert_array_equal(gens, [4, 5])
        assert t.snapshot.start_row == 160

    def test_out_of_window_entities_carry_coefficients_bitwise(self, tmp_path):
        """An entity whose rows all aged out of the window is NOT evicted:
        its previous-generation coefficients ride along verbatim (frozen,
        still servable) until eviction says otherwise."""
        rng = np.random.default_rng(43)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        write_part(corpus / "part-00000.avro", rng, 120, USERS)
        t = make_trainer(corpus, tmp_path / "ckpt", window_mode="sliding",
                         window_generations=2)
        t.poll_once()
        # u7 never appears again; after 2 generations its rows age out
        frozen = None
        for k in range(1, 4):
            write_part(corpus / f"part-{k:05d}.avro", rng, 30,
                       ["u0", "u1", "u2"])
            t.poll_once()
            m = t.models["per-user"]
            row = m.row_for_entity("u7")
            assert row >= 0, "u7 must stay in the tables (not evicted)"
            coeffs = np.asarray(m.coeffs)[row]
            if frozen is None:
                frozen = coeffs.copy()
            else:
                k_shared = min(len(frozen), len(coeffs))
                np.testing.assert_array_equal(coeffs[:k_shared],
                                              frozen[:k_shared])
        stats = t.last_result.active["per-user"]
        assert stats.get("n_carried", 0) > 0  # u3..u7 rode along
        # restart reproduces the carried rows bitwise
        t2 = make_trainer(corpus, tmp_path / "ckpt", window_mode="sliding",
                          window_generations=2)
        assert t2.models["per-user"].entity_ids == t.models["per-user"].entity_ids
        np.testing.assert_array_equal(
            np.asarray(t2.models["per-user"].coeffs),
            np.asarray(t.models["per-user"].coeffs),
        )

    def test_decay_mode_weights_are_age_derived_and_deterministic(self):
        from photon_ml_tpu.continuous import decay_weights

        weights = np.asarray([1.0, 2.0, 1.0, 0.5])
        gens = np.asarray([5, 4, 3, 5])
        out = decay_weights(weights, gens, current_gen=5, half_life=1.0)
        np.testing.assert_allclose(out, [1.0, 1.0, 0.25, 0.5], rtol=1e-6)
        again = decay_weights(weights, gens, current_gen=5, half_life=1.0)
        np.testing.assert_array_equal(out, again)  # bit-identical on replay

    def test_decay_mode_trains_and_replays_bitwise(self, tmp_path):
        rng = np.random.default_rng(47)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        write_part(corpus / "part-00000.avro", rng, 120, USERS)
        kw = dict(window_mode="decay", decay_half_life=1.0,
                  window_generations=3)
        t = make_trainer(corpus, tmp_path / "ckpt", **kw)
        t.poll_once()
        write_part(corpus / "part-00001.avro", rng, 40, ["u0"])
        r = t.poll_once()
        assert r is not None and r.kind == "delta"
        # a fresh restore replays to the same coefficients bitwise (the
        # decay weights recompute from persisted row ages)
        shutil.copytree(tmp_path / "ckpt", tmp_path / "ckpt2",
                        ignore=shutil.ignore_patterns("gen-00000002*"))
        t2 = make_trainer(corpus, tmp_path / "ckpt2", **kw)
        assert t2.generation == 1
        r2 = t2.poll_once()
        assert r2 is not None and r2.generation == 2
        np.testing.assert_array_equal(
            np.asarray(t2.models["per-user"].coeffs),
            np.asarray(t.models["per-user"].coeffs),
        )
        np.testing.assert_array_equal(
            np.asarray(t2.models["global"].model.coefficients.means),
            np.asarray(t.models["global"].model.coefficients.means),
        )

    def test_window_config_is_validated(self, tmp_path):
        with pytest.raises(ValueError, match="window_generations"):
            make_trainer(tmp_path, tmp_path / "c", window_mode="sliding")
        with pytest.raises(ValueError, match="decay_half_life"):
            make_trainer(tmp_path, tmp_path / "c", window_mode="decay")
        with pytest.raises(ValueError, match="no effect"):
            make_trainer(tmp_path, tmp_path / "c", window_generations=3)
        with pytest.raises(ValueError, match="window_mode"):
            make_trainer(tmp_path, tmp_path / "c", window_mode="bogus")
        with pytest.raises(ValueError, match="decay_half_life has no effect"):
            make_trainer(tmp_path, tmp_path / "c", window_mode="sliding",
                         window_generations=2, decay_half_life=1.0)
        with pytest.raises(ValueError, match="compact_every"):
            make_trainer(tmp_path, tmp_path / "c", compact_every=0)


# ---------------------------------------------------------- entity eviction


def _eviction_scenario(tmp_path, rng_seed=51, **extra):
    """Bootstrap all USERS, then two deltas targeting only u0: with
    evict_idle_generations=2 every other user evicts at generation 4."""
    rng = np.random.default_rng(rng_seed)
    corpus = tmp_path / "corpus"
    os.makedirs(corpus)
    write_part(corpus / "part-00000.avro", rng, 160, USERS)
    kw = dict(window_mode="sliding", window_generations=2,
              evict_idle_generations=2, **extra)
    t = make_trainer(corpus, tmp_path / "ckpt", **kw)
    t.poll_once()
    for k in (1, 2, 3):
        write_part(corpus / f"part-{k:05d}.avro", rng, 30, ["u0"])
        r = t.poll_once()
    return corpus, t, r, rng, kw


class TestEntityEviction:
    def test_idle_entities_evict_and_archive(self, tmp_path):
        corpus, t, r, rng, kw = _eviction_scenario(tmp_path)
        stats = r.active["per-user"]
        assert stats["n_evicted"] == 7  # u1..u7; u0 kept its data flowing
        assert t.models["per-user"].entity_ids == ("u0",)
        assert t.evicted["per-user"] == {f"u{i}" for i in range(1, 8)}
        archive = t.store.archive_load("per-user")
        assert set(archive["entity_ids"].tolist()) == t.evicted["per-user"]
        # the archived coefficients are the last pre-eviction rows, bitwise
        gens = list_generations(str(tmp_path / "ckpt"))
        prev = load_generation(dict(gens)[r.generation - 1])["models"]["per-user"]
        for e in sorted(t.evicted["per-user"]):
            src = prev.row_for_entity(e)
            dst = archive["entity_ids"].tolist().index(e)
            np.testing.assert_array_equal(
                archive["coeffs"][dst], np.asarray(prev.coeffs)[src]
            )
        # bookkeeping survives restart
        t2 = make_trainer(corpus, tmp_path / "ckpt", **kw)
        assert t2.evicted["per-user"] == t.evicted["per-user"]
        assert t2.models["per-user"].entity_ids == ("u0",)

    def test_evicted_entity_scores_like_never_seen_through_every_layer(
        self, tmp_path
    ):
        """The serving degradation contract (bitwise, three layers deep):
        an EVICTED entity's request scores exactly like a request whose
        entity never existed — engine, frontend, and HTTP transport."""
        from photon_ml_tpu.data.game_data import GameInput
        from photon_ml_tpu.serving import (
            FleetHTTPServer,
            FrontendConfig,
            ModelRouter,
            ReplicaSet,
            clear_engine_cache,
        )
        from photon_ml_tpu.serving.hotswap import serve_from_checkpoint
        from photon_ml_tpu.serving.transport import FleetClient
        import scipy.sparse as sp

        corpus, t, r, rng, kw = _eviction_scenario(tmp_path)
        assert "u3" in t.evicted["per-user"]
        dim = t.snapshot.index_maps["shardA"].size
        X = sp.csr_matrix(rng.normal(size=(6, dim)))

        def req(entity):
            return GameInput(
                features={"shardA": X.copy()},
                id_columns={"userId": np.asarray([entity] * 6, dtype=object)},
            )

        clear_engine_cache()
        try:
            frontend, _mgr = serve_from_checkpoint(
                str(tmp_path / "ckpt"),
                config=FrontendConfig(max_wait_ms=0.0),
            )
            assert frontend.generation == r.generation
            engine = frontend.engine
            evicted = engine.score(req("u3"))
            ghost = engine.score(req("zz-never-seen"))
            trained = engine.score(req("u0"))
            np.testing.assert_array_equal(evicted, ghost)  # the contract
            assert not np.array_equal(evicted, trained)  # u0 still personal
            # frontend coalescing path
            np.testing.assert_array_equal(
                frontend.score(req("u3"), timeout=30),
                frontend.score(req("zz-never-seen"), timeout=30),
            )
            frontend.close()

            # HTTP transport, byte-for-byte across the wire
            rs = ReplicaSet.from_checkpoint(
                str(tmp_path / "ckpt"), 1, name="m",
                config=FrontendConfig(max_wait_ms=0.0),
            )
            router = ModelRouter()
            router.add_model("m", rs)
            try:
                with FleetHTTPServer(router, port=0) as srv:
                    client = FleetClient(srv.host, srv.port)
                    out_evicted, gen_a = client.score("m", req("u3"))
                    out_ghost, gen_b = client.score("m", req("zz-never-seen"))
                    assert gen_a == gen_b == r.generation
                    assert out_evicted.dtype == out_ghost.dtype
                    np.testing.assert_array_equal(out_evicted, out_ghost)
                    np.testing.assert_array_equal(out_evicted, evicted)
            finally:
                router.close()
        finally:
            clear_engine_cache()

    def test_readmission_warm_starts_from_the_archive(self, tmp_path):
        corpus, t, r, rng, kw = _eviction_scenario(tmp_path)
        archived = t.store.archive_load("per-user")
        u1_row = archived["entity_ids"].tolist().index("u1")
        u1_coeffs = archived["coeffs"][u1_row].copy()
        assert np.any(u1_coeffs != 0)

        write_part(corpus / "part-00004.avro", rng, 30, ["u0", "u1"])
        r5 = t.poll_once()
        stats = r5.active["per-user"]
        assert stats["n_readmitted"] == 1
        assert "u1" not in t.evicted["per-user"]
        m = t.models["per-user"]
        assert m.row_for_entity("u1") >= 0  # back in the tables
        # and solved again (active): coefficients moved off the archive point
        assert stats["n_active"] >= 2

    def test_inject_archived_rows_remaps_by_global_column(self):
        """Unit proof of the warm-start injection: archived slots remap into
        the new layout by GLOBAL column id, unmatched columns zero-fill."""
        import jax.numpy as jnp

        from photon_ml_tpu.continuous import inject_archived_rows
        from photon_ml_tpu.models.game import RandomEffectModel

        model = RandomEffectModel(
            re_type="userId", feature_shard_id="s",
            task=TaskType.LOGISTIC_REGRESSION,
            entity_ids=("a", "b"),
            coeffs=jnp.zeros((2, 3)),
            proj_indices=jnp.asarray([[10, 20, 30], [10, 40, -1]]),
        )
        archive = {
            # archived layout for "b": columns (40, 10, 99) in ITS slot order
            "entity_ids": np.asarray(["b"]),
            "coeffs": np.asarray([[7.0, 5.0, 3.0]]),
            "proj": np.asarray([[40, 10, 99]]),
            "evicted_at": np.asarray([3]),
        }
        out, n = inject_archived_rows(model, archive, ["b"])
        assert n == 1
        np.testing.assert_array_equal(np.asarray(out.coeffs)[0], [0, 0, 0])
        # b's new layout is (10, 40, pad): 10 -> 5.0, 40 -> 7.0, pad -> 0
        np.testing.assert_array_equal(np.asarray(out.coeffs)[1], [5.0, 7.0, 0.0])
        # entities without an archive row stay zero (and don't count)
        same, n0 = inject_archived_rows(model, archive, ["a"])
        assert n0 == 0 and same is model


# -------------------------------------------- bounded-memory discipline


class TestBoundedMemory:
    def test_previous_view_is_dropped_eagerly(self, tmp_path):
        """Satellite regression: the trainer must not retain the previous
        generation's decoded snapshot once a pass completes — the old view's
        arrays become garbage the moment the grown view exists."""
        import gc
        import weakref

        rng = np.random.default_rng(61)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        write_part(corpus / "part-00000.avro", rng, 100, USERS)
        t = make_trainer(corpus, tmp_path / "ckpt")
        t.poll_once()
        old_labels = t.snapshot.data.labels
        ref = weakref.ref(old_labels)
        del old_labels
        write_part(corpus / "part-00001.avro", rng, 30, ["u0"])
        t.poll_once()
        gc.collect()
        assert ref() is None, (
            "the pre-delta view's arrays are still referenced after commit"
        )

    def test_window_keeps_resident_bytes_flat(self, tmp_path):
        """With a sliding window and equal-sized deltas, the store's resident
        corpus bytes are IDENTICAL across steady-state generations — O(hot
        tier), not O(history)."""
        rng = np.random.default_rng(63)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        write_part(corpus / "part-00000.avro", rng, 80, USERS)
        t = make_trainer(corpus, tmp_path / "ckpt", window_mode="sliding",
                         window_generations=2, compact_every=3)
        t.poll_once()
        resident = []
        for k in range(1, 7):
            write_part(corpus / f"part-{k:05d}.avro", rng, 40, USERS)
            t.poll_once()
            resident.append(t.store.resident_corpus_bytes)
        # steady state from generation 3 on: the view is exactly two deltas
        steady = resident[2:]
        assert max(steady) <= max(1, min(steady)) * 1.05
        # sanity: the unbounded trainer's resident bytes DO grow
        t_full = make_trainer(corpus, tmp_path / "ckpt-full")
        t_full.poll_once()
        assert t_full.store.resident_corpus_bytes > max(steady)

    def test_steady_pass_peak_memory_does_not_grow_with_history(self, tmp_path):
        """tracemalloc bound: a late windowed pass allocates no more than an
        early one (plus slack) — no step holds more than the hot tier plus
        block-sized cold reads."""
        import gc
        import tracemalloc

        rng = np.random.default_rng(65)
        corpus = tmp_path / "corpus"
        os.makedirs(corpus)
        write_part(corpus / "part-00000.avro", rng, 80, USERS)
        t = make_trainer(corpus, tmp_path / "ckpt", window_mode="sliding",
                         window_generations=2, compact_every=3,
                         cold_block_rows=64)

        def measured_pass(k):
            write_part(corpus / f"part-{k:05d}.avro", rng, 40, USERS)
            gc.collect()
            tracemalloc.start()
            t.poll_once()
            _, peak = tracemalloc.get_traced_memory()
            tracemalloc.stop()
            return peak

        t.poll_once()
        peaks = [measured_pass(k) for k in range(1, 9)]
        early = max(peaks[2:4])  # steady state begins at generation 3
        late = max(peaks[-2:])
        assert late <= early * 1.5 + (1 << 20), (peaks, early, late)


# -------------------------------------------- store fault-point chaos sweep


@pytest.fixture(scope="module")
def compact_chaos_scenario(tmp_path_factory):
    """Five generations committed under sliding window + eviction + row
    retention + archive age-out, with a compaction cadence that makes the
    PENDING delta an INCREMENTAL compaction pass: the swept generation 6
    plans evictions (continuous.evict), drops fully expired cold blocks and
    ages out the archive (continuous.cold_delete), reuses the surviving
    full blocks of the previous cold generation (continuous.cold_link),
    re-encodes only the seam/tail/delta (continuous.cold_write), folds
    (continuous.compact) and commits — so every store fault point sits ON
    the replayed path."""
    rng = np.random.default_rng(20260804)
    root = tmp_path_factory.mktemp("compact-chaos")
    corpus = root / "corpus"
    os.makedirs(corpus)
    # 160 bootstrap rows = exactly 10 pow2 blocks of 16: at the swept
    # compaction the retention cutoff (max_row_age_gens=5 at gen 6 -> keep
    # gens >= 2) drops them WHOLE, reuses the full gen-2..4 blocks of the
    # previous cold generation, and rewrites only its partial tail + delta
    write_part(corpus / "part-00000.avro", rng, 160, USERS)
    kw = dict(window_mode="sliding", window_generations=2,
              evict_idle_generations=1, compact_every=2, cold_block_rows=16,
              max_row_age_gens=5, archive_max_age_gens=2)
    base_ckpt = root / "ckpt-base"
    t = make_trainer(corpus, base_ckpt, **kw)
    t.poll_once()  # gen-1 bootstrap
    for k in (1, 2, 3, 4):
        write_part(corpus / f"part-{k:05d}.avro", rng, 30, ["u0"])
        t.poll_once()  # gens 2-5; compactions at 2 and 4 (4 reuses 2)
    write_part(corpus / "part-00005.avro", rng, 30, ["u0"])  # pending gen-6

    def run_loop(ckpt, export):
        t = make_trainer(corpus, ckpt, export_dir=export, **kw)
        while t.poll_once() is not None:
            pass
        return t

    ref_export = root / "export-ref"
    shutil.copytree(base_ckpt, root / "ckpt-ref")
    ref_trainer = run_loop(root / "ckpt-ref", ref_export)
    # the scenario genuinely exercises the machinery under sweep: an
    # incremental fold with reuse AND retention drops AND archive age-out
    r = ref_trainer.last_result
    assert r.compacted
    assert r.cold_stats["blocks_reused"] > 0
    assert r.cold_stats["blocks_dropped"] > 0
    assert r.cold_stats["rows_dropped"] > 0
    assert ref_trainer.evicted["per-user"]  # evictions happened (gen 3)
    # ... and their archive entries aged out on the swept pass
    assert ref_trainer.store.archive_load("per-user") is None
    return SimpleNamespace(
        base_ckpt=base_ckpt, ref_export=ref_export, run_loop=run_loop,
        ref_ckpt=root / "ckpt-ref",
    )


@pytest.mark.chaos
class TestStoreChaos:
    def test_compaction_pass_is_deterministic(self, compact_chaos_scenario, tmp_path):
        s = compact_chaos_scenario
        shutil.copytree(s.base_ckpt, tmp_path / "ckpt")
        s.run_loop(tmp_path / "ckpt", tmp_path / "export")
        assert_trees_identical(str(s.ref_export), str(tmp_path / "export"))
        # the durable store converges too: checkpoint generations AND the
        # cold tier/archive bytes are identical across runs
        assert_trees_identical(str(s.ref_ckpt), str(tmp_path / "ckpt"))

    @pytest.mark.parametrize("point", CONTINUOUS_POINTS + STORE_POINTS)
    def test_crash_anywhere_resumes_to_identical_generation_bytes(
        self, compact_chaos_scenario, tmp_path, point
    ):
        """Crash at EVERY continuous.* point during an evicting, compacting
        delta pass; restart; the exported generation, the committed
        checkpoints, the cold tier and the archive must all be bitwise an
        uninterrupted run's — compaction's only OBSERVABLE durable write is
        the atomic checkpoint commit."""
        s = compact_chaos_scenario
        shutil.copytree(s.base_ckpt, tmp_path / "ckpt")
        _, outcome = run_with_crash_at(
            lambda: s.run_loop(tmp_path / "ckpt", tmp_path / "export"),
            point,
        )
        assert outcome.crashed and outcome.restarts >= 1
        assert_trees_identical(str(s.ref_export), str(tmp_path / "export"))
        assert_trees_identical(str(s.ref_ckpt), str(tmp_path / "ckpt"))


class TestArchiveIntegrity:
    def test_archive_commits_as_one_atomic_file(self, tmp_path):
        """The archive's digest rides INSIDE the npz (one os.replace = the
        whole commit): no sidecar exists whose torn pairing with the content
        could brick every later pass (review finding on the two-rename
        window)."""
        corpus, t, r, rng, kw = _eviction_scenario(tmp_path)
        archive_dir = tmp_path / "ckpt" / "corpus-store" / "archive"
        files = sorted(os.listdir(archive_dir))
        assert files == ["per-user.npz"]  # no .sha256 sidecar, no stale tmp
        loaded = t.store.archive_load("per-user")
        assert set(loaded["entity_ids"].tolist()) == t.evicted["per-user"]

    def test_damaged_archive_fails_loudly(self, tmp_path):
        """Integrity is content-level (the digest covers array bytes, so rot
        in zip padding is benign by design): damage the ARRAYS and damage the
        CONTAINER, both must raise instead of re-admitting entities from
        garbage."""
        from photon_ml_tpu.continuous import ColdStoreCorruption

        corpus, t, r, rng, kw = _eviction_scenario(tmp_path)
        path = tmp_path / "ckpt" / "corpus-store" / "archive" / "per-user.npz"
        blob = bytearray(path.read_bytes())
        # dense flip: a tiny npz is mostly zip structure/padding, so hit
        # every 16th byte — array data cannot escape
        for i in range(0, len(blob), 16):
            blob[i] ^= 0xFF
        path.write_bytes(bytes(blob))
        with pytest.raises(ColdStoreCorruption):
            t.store.archive_load("per-user")
        # torn container (truncated mid-write by a crash on a non-atomic fs)
        path.write_bytes(bytes(blob[: len(blob) // 2]))
        with pytest.raises(ColdStoreCorruption, match="unreadable"):
            t.store.archive_load("per-user")


def test_contract_violation_mid_stage_leaves_a_retryable_trainer(tmp_path, monkeypatch):
    """A CorpusContractViolation AFTER the delta is staged (the torn-write
    verify bracket) must roll the stage back: the next poll retries cleanly
    instead of refusing with a pending stage."""
    rng = np.random.default_rng(67)
    corpus = tmp_path / "corpus"
    os.makedirs(corpus)
    write_part(corpus / "part-00000.avro", rng, 120, USERS)
    t = make_trainer(corpus, tmp_path / "ckpt")
    t.poll_once()
    write_part(corpus / "part-00001.avro", rng, 30, ["u0"])

    def explode(self, entries=None):
        raise CorpusContractViolation("file grew during ingest (simulated)")

    monkeypatch.setattr(CorpusManifest, "verify_sizes", explode)
    with pytest.raises(CorpusContractViolation):
        t.poll_once()
    assert t.snapshot.n_rows == 120  # the stage rolled back
    monkeypatch.undo()
    r = t.poll_once()  # and the retry commits normally
    assert r is not None and r.generation == 2 and r.n_rows == 150


def test_crash_orphaned_cold_generation_never_displaces_the_referenced_one(
    tmp_path,
):
    """An orphaned cold dir (renamed but never referenced because the commit
    crashed) is deleted at restore and NEVER counts toward keep_cold — it
    must not push the referenced generation (or its rollback step) out of
    retention."""
    rng = np.random.default_rng(69)
    corpus = tmp_path / "corpus"
    os.makedirs(corpus)
    write_part(corpus / "part-00000.avro", rng, 100, USERS)
    t = make_trainer(corpus, tmp_path / "ckpt", compact_every=1,
                     cold_block_rows=64)
    t.poll_once()
    write_part(corpus / "part-00001.avro", rng, 20, ["u0"])
    t.poll_once()  # cold-1 (rollback step) + cold-2 (referenced) on disk
    store_dir = tmp_path / "ckpt" / "corpus-store"
    assert sorted(n for n in os.listdir(store_dir) if n.startswith("cold-")) \
        == ["cold-00000001", "cold-00000002"]
    # fake a crashed future compaction: a cold dir no checkpoint references
    shutil.copytree(store_dir / "cold-00000002", store_dir / "cold-00000009")

    t2 = make_trainer(corpus, tmp_path / "ckpt", compact_every=1,
                      cold_block_rows=64)
    colds = sorted(n for n in os.listdir(store_dir) if n.startswith("cold-"))
    # orphan gone; the referenced generation AND its rollback step survive
    assert colds == ["cold-00000001", "cold-00000002"]
    assert t2.generation == 2 and t2.snapshot.n_rows == 120


def test_single_generation_window_survives_commit_fault(tmp_path):
    """window_generations=1 legally empties the view between passes: an
    in-pass failure must still roll back to the (empty) previous view and
    retry cleanly — not wedge behind a masked empty-materialize error."""
    rng = np.random.default_rng(71)
    corpus = tmp_path / "corpus"
    os.makedirs(corpus)
    write_part(corpus / "part-00000.avro", rng, 100, USERS)
    t = make_trainer(corpus, tmp_path / "ckpt", window_mode="sliding",
                     window_generations=1)
    t.poll_once()
    write_part(corpus / "part-00001.avro", rng, 30, ["u0"])
    with armed("continuous.commit:raise"):
        with pytest.raises(InjectedFault):
            t.poll_once()
    assert t.snapshot.n_rows == 0  # gen-1 rows aged out; stage rolled back
    r = t.poll_once()
    assert r is not None and r.generation == 2
    assert r.view_rows == 30 and r.n_rows == 130
    # and a restart materializes the same single-generation view
    t2 = make_trainer(corpus, tmp_path / "ckpt", window_mode="sliding",
                      window_generations=1)
    assert t2.snapshot.n_rows == 30


def test_readmission_below_lower_bound_keeps_the_archive(tmp_path):
    """A reappearing entity whose delta rows fall below
    active_data_lower_bound gets NO model row that pass: it must STAY
    evicted (archive intact) so a later, sufficient reappearance still
    warm-starts — dropping it from the evicted set would orphan the
    archived coefficients and zero-init it forever after."""
    coords = dict(
        parse_coordinate_configuration(c)
        for c in (FE_COORD, RE_COORD + ",active.data.lower.bound=3")
    )
    rng = np.random.default_rng(73)
    corpus = tmp_path / "corpus"
    os.makedirs(corpus)
    write_part(corpus / "part-00000.avro", rng, 160, USERS)

    def trainer():
        return ContinuousTrainer(
            ContinuousTrainerConfig(
                corpus_paths=[str(corpus)],
                checkpoint_directory=str(tmp_path / "ckpt"),
                task=TaskType.LOGISTIC_REGRESSION,
                coordinate_configurations=coords,
                shard_configurations=shard_configs(),
                window_mode="sliding", window_generations=2,
                evict_idle_generations=2,
            )
        )

    t = trainer()
    t.poll_once()
    for k in (1, 2, 3):
        write_part(corpus / f"part-{k:05d}.avro", rng, 30, ["u0"])
        r = t.poll_once()
    assert "u1" in t.evicted["per-user"]  # evicted at gen 4

    # u1 reappears with TWO rows: below the lower bound, no model row
    write_part(corpus / "part-00004.avro", rng, 2, ["u1"])
    r5 = t.poll_once()
    assert r5.active["per-user"]["n_readmitted"] == 0
    assert "u1" in t.evicted["per-user"]  # still evicted, archive intact
    assert t.models["per-user"].row_for_entity("u1") < 0

    # a sufficient reappearance later still warm-starts from the archive
    write_part(corpus / "part-00005.avro", rng, 12, ["u1"])
    r6 = t.poll_once()
    assert r6.active["per-user"]["n_readmitted"] == 1
    assert "u1" not in t.evicted["per-user"]
    assert t.models["per-user"].row_for_entity("u1") >= 0
