"""Fused Pallas GLM kernel vs the stock XLA objective (interpret mode on CPU).

The kernel itself is exercised interpreted (pl.pallas_call(interpret=True)) so
its numerics are validated without a TPU; the integration gate is exercised
through GLMObjective with the PHOTON_PALLAS_INTERPRET test hook.
"""

import contextlib
import os

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.dataset import LabeledData
from photon_ml_tpu.function.losses import (
    logistic_loss,
    poisson_loss,
    smoothed_hinge_loss,
    squared_loss,
)
from photon_ml_tpu.function.objective import GLMObjective
from photon_ml_tpu.normalization import NormalizationContext
from photon_ml_tpu.ops import pallas_glm

LOSSES = [logistic_loss, squared_loss, poisson_loss, smoothed_hinge_loss]


@contextlib.contextmanager
def pallas_interpret():
    """Enable the fused kernels in interpret mode, restoring prior state."""
    prev_env = os.environ.get("PHOTON_PALLAS_INTERPRET")
    pallas_glm.enable_pallas(True)
    os.environ["PHOTON_PALLAS_INTERPRET"] = "1"
    try:
        yield
    finally:
        pallas_glm.enable_pallas(None)
        if prev_env is None:
            del os.environ["PHOTON_PALLAS_INTERPRET"]
        else:
            os.environ["PHOTON_PALLAS_INTERPRET"] = prev_env


def _problem(rng, n=700, d=5, weights=None):
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.uniform(size=n) > 0.5).astype(np.float32)
    off = rng.normal(size=n).astype(np.float32) * 0.1
    w = np.ones(n, dtype=np.float32) if weights is None else weights
    coef = rng.normal(size=d).astype(np.float32) * 0.5
    return X, y, off, w, coef


def _reference_sums(loss, X, y, off, w, coef):
    z = X.astype(np.float64) @ coef.astype(np.float64) + off
    l, dz = loss.loss_and_dz(jnp.asarray(z), jnp.asarray(y.astype(np.float64)))
    with np.errstate(invalid="ignore"):  # 0 * inf rows are masked by the where
        wl = np.where(w != 0, w * np.asarray(l), 0.0)
        wdz = np.where(w != 0, w * np.asarray(dz), 0.0)
    return wl.sum(), X.T.astype(np.float64) @ wdz, wdz.sum()


@pytest.mark.parametrize("loss", LOSSES, ids=lambda l: l.name)
def test_fused_sums_match_reference(rng, loss):
    X, y, off, w, coef = _problem(rng)
    val, grad, wsum = pallas_glm.fused_loss_grad_sums(
        jnp.asarray(X), jnp.asarray(y), jnp.asarray(off), jnp.asarray(w),
        jnp.asarray(coef), jnp.float32(0.0),
        loss_and_dz=loss.loss_and_dz, interpret=True,
    )
    ref_val, ref_grad, ref_wsum = _reference_sums(loss, X, y, off, w, coef)
    np.testing.assert_allclose(float(val), ref_val, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(grad), ref_grad, rtol=2e-4, atol=1e-3)
    np.testing.assert_allclose(float(wsum), ref_wsum, rtol=2e-4, atol=1e-4)


def test_block_boundary_and_weight_masking(rng):
    """N not a multiple of the block size; weight-0 rows with overflowing
    margins must stay inert (the _weighted contract)."""
    n = pallas_glm.BLOCK_ROWS + 37
    X, y, off, w, coef = _problem(rng, n=n, d=3)
    w[::5] = 0.0
    off[::5] = 1e30  # exp overflows in the Poisson loss — must not poison sums
    val, grad, wsum = pallas_glm.fused_loss_grad_sums(
        jnp.asarray(X), jnp.asarray(y), jnp.asarray(off), jnp.asarray(w),
        jnp.asarray(coef), jnp.float32(0.0),
        loss_and_dz=poisson_loss.loss_and_dz, interpret=True,
    )
    ref_val, ref_grad, ref_wsum = _reference_sums(poisson_loss, X, y, off, w, coef)
    assert np.isfinite(float(val)) and np.isfinite(np.asarray(grad)).all()
    np.testing.assert_allclose(float(val), ref_val, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(grad), ref_grad, rtol=2e-4, atol=1e-3)


def test_objective_integration_matches_stock_path(rng):
    """GLMObjective.value_and_gradient via the fused gate == stock XLA path,
    including the normalization shift/factor algebra and the L2 term."""
    from photon_ml_tpu.data.matrix import DenseDesignMatrix

    X, y, off, w, coef = _problem(rng, n=300, d=4)
    X[:, -1] = 1.0  # intercept column (required for shift normalization)
    data = LabeledData(
        X=DenseDesignMatrix(jnp.asarray(X)),
        labels=jnp.asarray(y),
        offsets=jnp.asarray(off),
        weights=jnp.asarray(w),
    )
    shifts = rng.normal(size=4) * 0.1
    shifts[-1] = 0.0
    norm = NormalizationContext(
        factors=np.abs(rng.normal(size=4)) + 0.5, shifts=shifts, intercept_index=3
    )
    obj = GLMObjective(logistic_loss, norm)
    stock_v, stock_g = obj.value_and_gradient(data, jnp.asarray(coef), 0.7)

    with pallas_interpret():
        assert obj._fused_value_and_gradient(data, jnp.asarray(coef), 0.7) is not None
        fused_v, fused_g = obj.value_and_gradient(data, jnp.asarray(coef), 0.7)
    np.testing.assert_allclose(float(fused_v), float(stock_v), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(fused_g), np.asarray(stock_g), rtol=2e-4, atol=1e-4)


def test_gate_closed_by_default_and_for_wrong_dtypes(rng):
    X, y, off, w, coef = _problem(rng, n=64, d=3)
    from photon_ml_tpu.data.matrix import DenseDesignMatrix

    data = LabeledData(
        X=DenseDesignMatrix(jnp.asarray(X)), labels=jnp.asarray(y),
        offsets=jnp.asarray(off), weights=jnp.asarray(w),
    )
    obj = GLMObjective(logistic_loss)
    assert obj._fused_value_and_gradient(data, jnp.asarray(coef), 0.0) is None  # off

    with pallas_interpret():
        # f64 coefficients: precision contract keeps the stock path
        data64 = LabeledData(
            X=DenseDesignMatrix(jnp.asarray(X, dtype=jnp.float64)),
            labels=jnp.asarray(y), offsets=jnp.asarray(off), weights=jnp.asarray(w),
        )
        assert (
            obj._fused_value_and_gradient(data64, jnp.asarray(coef, jnp.float64), 0.0)
            is None
        )
        # vmapped-construction objects opt out
        no_fuse = GLMObjective(logistic_loss, allow_fused=False)
        assert no_fuse._fused_value_and_gradient(data, jnp.asarray(coef), 0.0) is None


def test_solver_convergence_through_fused_path(rng):
    """An L-BFGS solve with the fused evaluations reaches the stock optimum."""
    from photon_ml_tpu.function.objective import make_value_and_grad
    from photon_ml_tpu.optimization import minimize_lbfgs
    from photon_ml_tpu.data.matrix import DenseDesignMatrix

    X, y, off, w, coef = _problem(rng, n=400, d=6)
    data = LabeledData(
        X=DenseDesignMatrix(jnp.asarray(X)), labels=jnp.asarray(y),
        offsets=jnp.asarray(off), weights=jnp.asarray(w),
    )
    obj = GLMObjective(logistic_loss)
    vg = make_value_and_grad(obj, data, l2_weight=1.0)
    stock = minimize_lbfgs(vg, jnp.zeros(6, jnp.float32), tolerance=1e-10, max_iterations=100)

    with pallas_interpret():
        fused = minimize_lbfgs(
            vg, jnp.zeros(6, jnp.float32), tolerance=1e-10, max_iterations=100
        )
    np.testing.assert_allclose(
        np.asarray(fused.coefficients), np.asarray(stock.coefficients), atol=5e-4
    )


@pytest.mark.parametrize("loss", [logistic_loss, squared_loss, poisson_loss], ids=lambda l: l.name)
def test_fused_hvp_matches_reference(rng, loss):
    X, y, off, w, coef = _problem(rng, n=pallas_glm.BLOCK_ROWS + 51, d=6)
    w[::7] = 0.0
    v = rng.normal(size=6).astype(np.float32)
    vec, usum = pallas_glm.fused_hessian_vector_sums(
        jnp.asarray(X), jnp.asarray(y), jnp.asarray(off), jnp.asarray(w),
        jnp.asarray(coef), jnp.float32(0.0), jnp.asarray(v), jnp.float32(0.0),
        dzz=loss.dzz, interpret=True,
    )
    z = X.astype(np.float64) @ coef.astype(np.float64) + off
    d2 = np.asarray(loss.dzz(jnp.asarray(z), jnp.asarray(y.astype(np.float64))))
    dv = X.astype(np.float64) @ v.astype(np.float64)
    u = np.where(w != 0, w * d2 * dv, 0.0)
    np.testing.assert_allclose(np.asarray(vec), X.T.astype(np.float64) @ u, rtol=2e-4, atol=1e-3)
    np.testing.assert_allclose(float(usum), u.sum(), rtol=2e-4, atol=1e-4)


def test_tron_solve_through_fused_hvp(rng):
    """A TRON solve with fused evaluations (value+grad AND HVP) matches stock."""
    from photon_ml_tpu.function.objective import make_value_and_grad
    from photon_ml_tpu.optimization import minimize_tron
    from photon_ml_tpu.data.matrix import DenseDesignMatrix

    X, y, off, w, coef = _problem(rng, n=400, d=5)
    data = LabeledData(
        X=DenseDesignMatrix(jnp.asarray(X)), labels=jnp.asarray(y),
        offsets=jnp.asarray(off), weights=jnp.asarray(w),
    )
    obj = GLMObjective(logistic_loss)
    vg = make_value_and_grad(obj, data, l2_weight=0.5)
    hvp = lambda x, v: obj.hessian_vector(data, x, v, 0.5)
    stock = minimize_tron(vg, hvp, jnp.zeros(5, jnp.float32), tolerance=1e-10, max_iterations=60)

    with pallas_interpret():
        assert obj._fused_hessian_vector(
            data, jnp.zeros(5, jnp.float32), jnp.ones(5, jnp.float32), 0.5
        ) is not None
        fused = minimize_tron(
            vg, hvp, jnp.zeros(5, jnp.float32), tolerance=1e-10, max_iterations=60
        )
    np.testing.assert_allclose(
        np.asarray(fused.coefficients), np.asarray(stock.coefficients), atol=5e-4
    )


def test_fused_hvp_with_normalization(rng):
    from photon_ml_tpu.data.matrix import DenseDesignMatrix

    X, y, off, w, coef = _problem(rng, n=250, d=4)
    X[:, -1] = 1.0
    shifts = rng.normal(size=4) * 0.1
    shifts[-1] = 0.0
    norm = NormalizationContext(
        factors=np.abs(rng.normal(size=4)) + 0.5, shifts=shifts, intercept_index=3
    )
    data = LabeledData(
        X=DenseDesignMatrix(jnp.asarray(X)), labels=jnp.asarray(y),
        offsets=jnp.asarray(off), weights=jnp.asarray(w),
    )
    obj = GLMObjective(logistic_loss, norm)
    v = jnp.asarray(rng.normal(size=4).astype(np.float32))
    stock = obj.hessian_vector(data, jnp.asarray(coef), v, 0.3)

    with pallas_interpret():
        fused = obj.hessian_vector(data, jnp.asarray(coef), v, 0.3)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(stock), rtol=2e-4, atol=1e-4)


def test_fused_kernels_bf16_storage(rng):
    """bf16 design-matrix storage: both kernels run the bf16 MXU branch and
    stay within bf16 rounding of the f64 reference (the _mxu_dot contract)."""
    X, y, off, w, coef = _problem(rng, n=300, d=4)
    Xb = jnp.asarray(X, dtype=jnp.bfloat16)
    val, grad, wsum = pallas_glm.fused_loss_grad_sums(
        Xb, jnp.asarray(y), jnp.asarray(off), jnp.asarray(w),
        jnp.asarray(coef), jnp.float32(0.0),
        loss_and_dz=logistic_loss.loss_and_dz, interpret=True,
    )
    Xr = np.asarray(Xb).astype(np.float64)  # the rounded values ARE the data
    ref_val, ref_grad, ref_wsum = _reference_sums(logistic_loss, Xr, y, off, w, coef)
    np.testing.assert_allclose(float(val), ref_val, rtol=3e-2)
    np.testing.assert_allclose(np.asarray(grad), ref_grad, rtol=4e-2, atol=0.5)
    np.testing.assert_allclose(float(wsum), ref_wsum, rtol=4e-2, atol=0.1)
    zr = Xr @ np.asarray(coef, np.float64) + off

    v = rng.normal(size=4).astype(np.float32)
    vec, usum = pallas_glm.fused_hessian_vector_sums(
        Xb, jnp.asarray(y), jnp.asarray(off), jnp.asarray(w),
        jnp.asarray(coef), jnp.float32(0.0), jnp.asarray(v), jnp.float32(0.0),
        dzz=logistic_loss.dzz, interpret=True,
    )
    d2 = np.asarray(logistic_loss.dzz(jnp.asarray(zr), jnp.asarray(y.astype(np.float64))))
    u = w * d2 * (Xr @ v.astype(np.float64))
    np.testing.assert_allclose(np.asarray(vec), Xr.T @ u, rtol=4e-2, atol=0.5)
    np.testing.assert_allclose(float(usum), u.sum(), rtol=4e-2, atol=0.1)


@pytest.mark.parametrize("loss", [logistic_loss, squared_loss, poisson_loss], ids=lambda l: l.name)
def test_fused_hessian_matrix_matches_reference(rng, loss):
    X, y, off, w, coef = _problem(rng, n=pallas_glm.HESS_BLOCK_ROWS + 33, d=5)
    w[::6] = 0.0
    H = pallas_glm.fused_hessian_matrix(
        jnp.asarray(X), jnp.asarray(y), jnp.asarray(off), jnp.asarray(w),
        jnp.asarray(coef), jnp.float32(0.0),
        jnp.zeros(5, jnp.float32), jnp.ones(5, jnp.float32),
        dzz=loss.dzz, interpret=True,
    )
    z = X.astype(np.float64) @ coef.astype(np.float64) + off
    d2 = np.where(w != 0, w * np.asarray(
        loss.dzz(jnp.asarray(z), jnp.asarray(y.astype(np.float64)))
    ), 0.0)
    ref = X.T.astype(np.float64) @ (X.astype(np.float64) * d2[:, None])
    np.testing.assert_allclose(np.asarray(H), ref, rtol=2e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(H), np.asarray(H).T, atol=1e-5)  # symmetric


def test_fused_hessian_matrix_bf16_storage(rng):
    """bf16 storage upcasts the block to f32 BEFORE normalization (the stock
    path's reduction-dtype contract)."""
    X, y, off, w, coef = _problem(rng, n=200, d=4)
    Xb = jnp.asarray(X, dtype=jnp.bfloat16)
    H = pallas_glm.fused_hessian_matrix(
        Xb, jnp.asarray(y), jnp.asarray(off), jnp.asarray(w),
        jnp.asarray(coef), jnp.float32(0.0),
        jnp.zeros(4, jnp.float32), jnp.ones(4, jnp.float32),
        dzz=logistic_loss.dzz, interpret=True,
    )
    Xr = np.asarray(Xb).astype(np.float64)  # the rounded values ARE the data
    z = Xr @ np.asarray(coef, np.float64) + off
    d2 = w * np.asarray(logistic_loss.dzz(jnp.asarray(z), jnp.asarray(y.astype(np.float64))))
    ref = Xr.T @ (Xr * d2[:, None])
    np.testing.assert_allclose(np.asarray(H), ref, rtol=4e-2, atol=0.5)


def test_fused_hessian_matrix_through_objective_with_normalization(rng):
    from photon_ml_tpu.data.matrix import DenseDesignMatrix

    X, y, off, w, coef = _problem(rng, n=200, d=4)
    X[:, -1] = 1.0
    shifts = rng.normal(size=4) * 0.1
    shifts[-1] = 0.0
    norm = NormalizationContext(
        factors=np.abs(rng.normal(size=4)) + 0.5, shifts=shifts, intercept_index=3
    )
    data = LabeledData(
        X=DenseDesignMatrix(jnp.asarray(X)), labels=jnp.asarray(y),
        offsets=jnp.asarray(off), weights=jnp.asarray(w),
    )
    obj = GLMObjective(logistic_loss, norm)
    stock = obj.hessian_matrix(data, jnp.asarray(coef), 0.4)
    with pallas_interpret():
        assert obj._fused_hessian_matrix(data, jnp.asarray(coef), 0.4) is not None
        fused = obj.hessian_matrix(data, jnp.asarray(coef), 0.4)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(stock), rtol=2e-4, atol=1e-4)


def test_newton_solve_through_fused_hessian(rng):
    """A NEWTON solve with all three fused kernels matches the stock optimum."""
    from photon_ml_tpu.optimization import minimize_newton
    from photon_ml_tpu.function.objective import make_value_and_grad
    from photon_ml_tpu.data.matrix import DenseDesignMatrix

    X, y, off, w, coef = _problem(rng, n=400, d=5)
    data = LabeledData(
        X=DenseDesignMatrix(jnp.asarray(X)), labels=jnp.asarray(y),
        offsets=jnp.asarray(off), weights=jnp.asarray(w),
    )
    obj = GLMObjective(logistic_loss)
    vg = make_value_and_grad(obj, data, l2_weight=0.8)
    hess = lambda x: obj.hessian_matrix(data, x, 0.8)
    stock = minimize_newton(vg, hess, jnp.zeros(5, jnp.float32), tolerance=1e-10)
    with pallas_interpret():
        fused = minimize_newton(vg, hess, jnp.zeros(5, jnp.float32), tolerance=1e-10)
    np.testing.assert_allclose(
        np.asarray(fused.coefficients), np.asarray(stock.coefficients), atol=5e-4
    )


def test_full_game_step_with_fused_fe(rng):
    """The single-device GAME step traces and matches stock with the fused
    kernels engaged — the exact lowering the TPU bench's pallas variant runs."""
    import scipy.sparse as sp

    from photon_ml_tpu.data.random_effect import build_random_effect_dataset
    from photon_ml_tpu.optimization.common import OptimizerConfig
    from photon_ml_tpu.optimization.config import (
        GLMOptimizationConfiguration,
        RegularizationContext,
    )
    from photon_ml_tpu.parallel import (
        build_sharded_game_data,
        make_jitted_game_step,
        make_mesh,
    )
    from photon_ml_tpu.parallel.game import init_game_params
    from photon_ml_tpu.types import OptimizerType, RegularizationType, TaskType

    n, d, n_users = 400, 6, 10
    X = rng.normal(size=(n, d)).astype(np.float32)
    users = np.arange(n) % n_users
    y = ((X @ rng.normal(size=d)) + rng.normal(size=n_users)[users] > 0).astype(
        np.float64
    )
    re_feat = sp.csr_matrix(np.ones((n, 1), np.float32))
    ds = build_random_effect_dataset(
        re_feat, users, "u", labels=y, intercept_index=0, dtype=jnp.float32
    )
    mesh = make_mesh(1)
    data = build_sharded_game_data(X, y, [ds], mesh, dtype=jnp.float32)
    cfg = GLMOptimizationConfiguration(
        optimizer_config=OptimizerConfig(
            optimizer_type=OptimizerType.NEWTON, max_iterations=10
        ),
        regularization_context=RegularizationContext(RegularizationType.L2),
        regularization_weight=1.0,
    )

    def run():
        step = make_jitted_game_step(
            data, TaskType.LOGISTIC_REGRESSION, cfg, [cfg], mesh
        )
        params, diag = step(init_game_params(data, mesh))
        return np.asarray(params["fixed"]), float(diag["fe_value"])

    stock_coef, stock_val = run()
    with pallas_interpret():
        # guard: the fused path must actually be eligible for this setup,
        # otherwise the parity below silently compares stock against stock
        assert pallas_glm.should_fuse(d)
        from photon_ml_tpu.data.matrix import DenseDesignMatrix
        from photon_ml_tpu.function.objective import GLMObjective
        from photon_ml_tpu.function.losses import logistic_loss

        assert GLMObjective(logistic_loss)._fused_eligible(
            data.fe_X if isinstance(data.fe_X, DenseDesignMatrix) else None,
            jnp.zeros((d,), jnp.float32),
        )
        fused_coef, fused_val = run()
    np.testing.assert_allclose(fused_coef, stock_coef, atol=5e-4)
    np.testing.assert_allclose(fused_val, stock_val, rtol=1e-4)


def test_shard_mapped_solver_matches_gspmd(rng):
    """shard_mapped_glm_solver (explicit shard_map + psum) must reach the same
    optimum as the stock GSPMD solve on the 8-device mesh — with the kernels
    OFF it is purely the explicit-collective form of the same math."""
    from photon_ml_tpu.data.dataset import LabeledData
    from photon_ml_tpu.data.matrix import DenseDesignMatrix
    from photon_ml_tpu.optimization.common import OptimizerConfig
    from photon_ml_tpu.optimization.solver_cache import (
        glm_solver,
        shard_mapped_glm_solver,
    )
    from photon_ml_tpu.parallel import make_mesh
    from photon_ml_tpu.parallel.glm import shard_labeled_data
    from photon_ml_tpu.types import TaskType, VarianceComputationType

    n, d = 512, 6
    X = rng.normal(size=(n, d))
    y = ((X @ rng.normal(size=d)) > 0).astype(np.float64)
    data = LabeledData.build(DenseDesignMatrix(jnp.asarray(X)), y, dtype=jnp.float64)
    mesh = make_mesh(8)
    data_m, _ = shard_labeled_data(data, mesh)

    cfg = OptimizerConfig(max_iterations=60, tolerance=1e-10)
    l2 = jnp.asarray(1.0, jnp.float64)
    l1 = jnp.asarray(0.0, jnp.float64)
    x0 = jnp.zeros((d,), jnp.float64)
    empty = jnp.zeros((0,), jnp.float64)

    from photon_ml_tpu.normalization import NO_NORMALIZATION

    ref, _ = glm_solver(
        TaskType.LOGISTIC_REGRESSION, cfg, False, False, False,
        VarianceComputationType.NONE,
    )(data, x0, l2, l1, empty, empty, NO_NORMALIZATION)
    got = shard_mapped_glm_solver(TaskType.LOGISTIC_REGRESSION, cfg, False, mesh)(
        data_m, x0, l2, l1
    )
    np.testing.assert_allclose(
        np.asarray(got.coefficients), np.asarray(ref.coefficients), atol=1e-8
    )
    assert float(got.value) == pytest.approx(float(ref.value), rel=1e-10)


def test_full_game_step_shard_map_multichip(rng):
    """With the kernels enabled on a MULTI-device mesh, the fixed-effect solve
    takes the shard_map route (per-device fused blocks + explicit psum) and
    matches the stock GSPMD result — the single-chip-only restriction on the
    Pallas path is lifted."""
    import scipy.sparse as sp

    from photon_ml_tpu.data.random_effect import build_random_effect_dataset
    from photon_ml_tpu.optimization.common import OptimizerConfig
    from photon_ml_tpu.optimization.config import (
        GLMOptimizationConfiguration,
        RegularizationContext,
    )
    from photon_ml_tpu.parallel import (
        build_sharded_game_data,
        make_jitted_game_step,
        make_mesh,
    )
    from photon_ml_tpu.parallel.game import init_game_params
    from photon_ml_tpu.types import RegularizationType, TaskType

    n, d, n_users = 400, 6, 10
    X = rng.normal(size=(n, d)).astype(np.float32)
    users = np.arange(n) % n_users
    y = ((X @ rng.normal(size=d)) + rng.normal(size=n_users)[users] > 0).astype(
        np.float64
    )
    re_feat = sp.csr_matrix(np.ones((n, 1), np.float32))
    ds = build_random_effect_dataset(
        re_feat, users, "u", labels=y, intercept_index=0, dtype=jnp.float32
    )
    mesh = make_mesh(8)
    data = build_sharded_game_data(X, y, [ds], mesh, dtype=jnp.float32)
    cfg = GLMOptimizationConfiguration(
        optimizer_config=OptimizerConfig(max_iterations=40),
        regularization_context=RegularizationContext(RegularizationType.L2),
        regularization_weight=1.0,
    )

    def run():
        step = make_jitted_game_step(
            data, TaskType.LOGISTIC_REGRESSION, cfg, [cfg], mesh
        )
        params, diag = step(init_game_params(data, mesh))
        return np.asarray(params["fixed"]), float(diag["fe_value"])

    stock_coef, stock_val = run()
    with pallas_interpret():
        assert pallas_glm.should_fuse(d, per_device=True)
        fused_coef, fused_val = run()
    np.testing.assert_allclose(fused_coef, stock_coef, atol=5e-4)
    np.testing.assert_allclose(fused_val, stock_val, rtol=1e-4)


@pytest.mark.parametrize("opt", ["TRON", "NEWTON"])
def test_shard_mapped_solver_second_order_parity(rng, opt):
    """The psum'd objective must serve the second-order paths too: TRON's
    per-CG-step HVP and NEWTON's per-iteration full Hessian are data sums
    with replicated algebra on top — shard_map must reach the stock optimum."""
    from photon_ml_tpu.data.dataset import LabeledData
    from photon_ml_tpu.data.matrix import DenseDesignMatrix
    from photon_ml_tpu.normalization import NO_NORMALIZATION
    from photon_ml_tpu.optimization.common import OptimizerConfig
    from photon_ml_tpu.optimization.solver_cache import (
        glm_solver,
        shard_mapped_glm_solver,
    )
    from photon_ml_tpu.parallel import make_mesh
    from photon_ml_tpu.parallel.glm import shard_labeled_data
    from photon_ml_tpu.types import OptimizerType, TaskType, VarianceComputationType

    n, d = 512, 6
    X = rng.normal(size=(n, d))
    y = ((X @ rng.normal(size=d)) > 0).astype(np.float64)
    data = LabeledData.build(DenseDesignMatrix(jnp.asarray(X)), y, dtype=jnp.float64)
    mesh = make_mesh(8)
    data_m, _ = shard_labeled_data(data, mesh)

    cfg = OptimizerConfig(
        optimizer_type=OptimizerType[opt], max_iterations=30, tolerance=1e-10
    )
    l2 = jnp.asarray(1.0, jnp.float64)
    l1 = jnp.asarray(0.0, jnp.float64)
    x0 = jnp.zeros((d,), jnp.float64)
    empty = jnp.zeros((0,), jnp.float64)

    ref, _ = glm_solver(
        TaskType.LOGISTIC_REGRESSION, cfg, False, False, False,
        VarianceComputationType.NONE,
    )(data, x0, l2, l1, empty, empty, NO_NORMALIZATION)
    got = shard_mapped_glm_solver(TaskType.LOGISTIC_REGRESSION, cfg, False, mesh)(
        data_m, x0, l2, l1
    )
    np.testing.assert_allclose(
        np.asarray(got.coefficients), np.asarray(ref.coefficients), atol=1e-7
    )


def test_shard_mapped_solver_rejects_sparse(rng):
    """nnz-sharded COO inside shard_map would psum partial-margin losses —
    reject it loudly; sparse problems take the GSPMD lowering."""
    import scipy.sparse as sp

    from photon_ml_tpu.data.dataset import LabeledData
    from photon_ml_tpu.data.matrix import as_design_matrix
    from photon_ml_tpu.optimization.common import OptimizerConfig
    from photon_ml_tpu.optimization.solver_cache import shard_mapped_glm_solver
    from photon_ml_tpu.parallel import make_mesh
    from photon_ml_tpu.types import TaskType

    n, d = 64, 4
    X = sp.random(n, d, density=0.3, random_state=0, format="csr")
    y = (rng.random(n) < 0.5).astype(np.float64)
    data = LabeledData.build(as_design_matrix(X), y, dtype=jnp.float64)
    mesh = make_mesh(8)
    solve = shard_mapped_glm_solver(
        TaskType.LOGISTIC_REGRESSION, OptimizerConfig(max_iterations=5), False, mesh
    )
    with pytest.raises(TypeError, match="dense sample-sharded"):
        solve(
            data,
            jnp.zeros((d,), jnp.float64),
            jnp.asarray(1.0, jnp.float64),
            jnp.asarray(0.0, jnp.float64),
        )
